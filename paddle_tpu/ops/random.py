"""Random ops (reference: python/paddle/tensor/random.py).

Eager API draws from the global stateful Generator (core/generator.py); every
op also accepts an explicit ``key=`` for functional/jit use — the idiomatic
JAX style that keeps compiled code deterministic and replayable.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import generator as gen
from ..core.tensor import Tensor


def _key(key):
    return key if key is not None else gen.next_key()


def _dt(dtype, default=None):
    d = dtypes.to_jax_dtype(dtype)
    return d if d is not None else (default or dtypes.default_float_dtype().np_dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None, name=None, key=None) -> Tensor:
    return Tensor(jax.random.uniform(_key(key), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None, key=None) -> Tensor:
    return Tensor(jax.random.normal(_key(key), _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None, key=None) -> Tensor:
    return randn(shape, dtype, key=key)


def normal(mean=0.0, std=1.0, shape=None, name=None, key=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_key(key), shp) * s + m)
    shp = _shape(shape if shape is not None else [1])
    return Tensor(jax.random.normal(_key(key), shp,
                                    dtypes.default_float_dtype().np_dtype) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None, key=None) -> Tensor:
    if seed:
        key = jax.random.PRNGKey(seed)
    return Tensor(jax.random.uniform(_key(key), _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None, key=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(key), _shape(shape), low, high,
                                     _dt(dtype, np.int32)))


def randint_like(x, low=0, high=None, dtype=None, name=None, key=None) -> Tensor:
    if high is None:
        low, high = 0, low
    dt = _dt(dtype, np.dtype(x._data.dtype)) if dtype else x._data.dtype
    return Tensor(jax.random.randint(_key(key), x._data.shape, low, high, dt))


def randperm(n, dtype="int64", name=None, key=None) -> Tensor:
    return Tensor(jax.random.permutation(_key(key), int(n)).astype(_dt(dtype, np.int32)))


def multinomial(x, num_samples=1, replacement=False, name=None, key=None) -> Tensor:
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(arr, 1e-30))
    k = _key(key)
    if replacement:
        if arr.ndim == 1:
            out = jax.random.categorical(k, logits, shape=(num_samples,))
        else:
            out = jax.random.categorical(k, logits[:, None, :], axis=-1,
                                         shape=(arr.shape[0], num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(k, arr.shape)
        scores = logits + g
        out = jnp.argsort(-scores, axis=-1)[..., :num_samples]
    return Tensor(out.astype(jnp.int32))


def bernoulli(x, name=None, key=None) -> Tensor:
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(_key(key), arr).astype(arr.dtype))


def poisson(x, name=None, key=None) -> Tensor:
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(_key(key), arr).astype(arr.dtype))


def exponential_(x, lam=1.0, name=None, key=None) -> Tensor:
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    sample = jax.random.exponential(_key(key), arr.shape).astype(arr.dtype) / lam
    if isinstance(x, Tensor):
        x._data = sample
        return x
    return Tensor(sample)


def rand_like(x, dtype=None, key=None) -> Tensor:
    dt = _dt(dtype) if dtype else x._data.dtype
    return Tensor(jax.random.uniform(_key(key), x._data.shape, dt))


def randn_like(x, dtype=None, name=None, key=None) -> Tensor:
    dt = _dt(dtype) if dtype else x._data.dtype
    return Tensor(jax.random.normal(_key(key), x._data.shape, dt))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None, key=None):
    from .registry import call_op

    k = _key(key)

    def fn(logits):
        g = jax.random.gumbel(k, jnp.shape(logits), logits.dtype)
        y = jax.nn.softmax((logits + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis) \
                if hasattr(jnp, "put_along_axis") else \
                y_hard.at[jnp.indices(y.shape)[0]].set(0)  # fallback below
            oh = jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis], axis=axis,
                                dtype=y.dtype)
            return oh + jax.lax.stop_gradient(-y) + y
        return y

    return call_op("gumbel_softmax", fn, (x,), {})

"""Reduction ops (reference: python/paddle/tensor/math.py reductions,
paddle/phi/kernels/funcs/reduce_function.h). XLA maps these onto the TPU's
vector unit reduce trees; keepdim handling mirrors the paddle API."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from .registry import register_op


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@register_op()
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = dtypes.to_jax_dtype(dtype)
    return jnp.sum(x, axis=_axis(axis), dtype=dt, keepdims=keepdim)


@register_op()
def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def amax(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def amin(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return jnp.prod(x, axis=_axis(axis), dtype=dtypes.to_jax_dtype(dtype),
                    keepdims=keepdim)


@register_op()
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@register_op()
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@register_op()
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.nansum(x, axis=_axis(axis), dtype=dtypes.to_jax_dtype(dtype),
                      keepdims=keepdim)


@register_op()
def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@register_op(differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmax(x, axis=_axis(axis), keepdims=keepdim and axis is not None)
    return out.astype(dtypes.to_jax_dtype(dtype))


@register_op(differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmin(x, axis=_axis(axis), keepdims=keepdim and axis is not None)
    return out.astype(dtypes.to_jax_dtype(dtype))


@register_op(differentiable=False)
def all(x, axis=None, keepdim=False, name=None):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@register_op(differentiable=False)
def any(x, axis=None, keepdim=False, name=None):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@register_op(differentiable=False)
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtypes.to_jax_dtype(dtype))


@register_op()
def cumprod(x, dim=None, dtype=None, name=None):
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtypes.to_jax_dtype(dtype))


@register_op()
def median(x, axis=None, keepdim=False, mode="avg", name=None):
    if mode == "avg":
        return jnp.median(x, axis=_axis(axis), keepdims=keepdim)
    # 'min' mode: lower of the two middles
    ax = _axis(axis)
    if ax is None:
        flat = x.reshape(-1)
        n = flat.shape[0]
        return jnp.sort(flat)[(n - 1) // 2]
    n = x.shape[ax]
    srt = jnp.sort(x, axis=ax)
    return jnp.take(srt, (n - 1) // 2, axis=ax) if not keepdim else \
        jnp.take(srt, jnp.asarray([(n - 1) // 2]), axis=ax)


@register_op()
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@register_op()
def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return jnp.quantile(x, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim,
                        method=interpolation)


@register_op()
def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.nanquantile(x, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim)


@register_op(differentiable=False)
def mode(x, axis=-1, keepdim=False, name=None):
    # jax.scipy.stats.mode only reduces axis 0 correctly in this version.
    # Sort-then-run-length (O(n log n) time, O(n) memory — a pairwise
    # equality matrix would be O(n^2) and OOM on long axes): each sorted
    # element's run is [first, last] where first is the running max of
    # run-start indices and last the reverse running min of run-end
    # indices; argmax of run length picks the smallest modal value.
    xm = jnp.moveaxis(x, axis, -1)
    xs = jnp.sort(xm, axis=-1)
    n = xs.shape[-1]
    iota = jnp.arange(n)
    changed = xs[..., 1:] != xs[..., :-1]
    new_run = jnp.concatenate(
        [jnp.ones(xs.shape[:-1] + (1,), bool), changed], axis=-1)
    run_end = jnp.concatenate(
        [changed, jnp.ones(xs.shape[:-1] + (1,), bool)], axis=-1)
    first = jax.lax.cummax(jnp.where(new_run, iota, 0), axis=xs.ndim - 1)
    last = jax.lax.cummin(jnp.where(run_end, iota, n - 1),
                          axis=xs.ndim - 1, reverse=True)
    cnt = last - first + 1
    k = jnp.argmax(cnt, axis=-1)
    modes = jnp.take_along_axis(xs, k[..., None], axis=-1)[..., 0]
    count = jnp.take_along_axis(cnt, k[..., None], axis=-1)[..., 0]
    if keepdim:
        modes = jnp.expand_dims(modes, axis)
        count = jnp.expand_dims(count, axis)
    return modes, count

"""Comparison & logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .registry import register_op


@register_op(differentiable=False)
def equal(x, y, name=None):
    return jnp.equal(x, y)


@register_op(differentiable=False)
def not_equal(x, y, name=None):
    return jnp.not_equal(x, y)


@register_op(differentiable=False)
def greater_than(x, y, name=None):
    return jnp.greater(x, y)


@register_op(differentiable=False)
def greater_equal(x, y, name=None):
    return jnp.greater_equal(x, y)


@register_op(differentiable=False)
def less_than(x, y, name=None):
    return jnp.less(x, y)


@register_op(differentiable=False)
def less_equal(x, y, name=None):
    return jnp.less_equal(x, y)


@register_op(differentiable=False)
def logical_and(x, y, out=None, name=None):
    return jnp.logical_and(x, y)


@register_op(differentiable=False)
def logical_or(x, y, out=None, name=None):
    return jnp.logical_or(x, y)


@register_op(differentiable=False)
def logical_xor(x, y, out=None, name=None):
    return jnp.logical_xor(x, y)


@register_op(differentiable=False)
def logical_not(x, out=None, name=None):
    return jnp.logical_not(x)


@register_op(differentiable=False)
def bitwise_and(x, y, out=None, name=None):
    return jnp.bitwise_and(x, y)


@register_op(differentiable=False)
def bitwise_or(x, y, out=None, name=None):
    return jnp.bitwise_or(x, y)


@register_op(differentiable=False)
def bitwise_xor(x, y, out=None, name=None):
    return jnp.bitwise_xor(x, y)


@register_op(differentiable=False)
def bitwise_not(x, out=None, name=None):
    return jnp.bitwise_not(x)


@register_op(differentiable=False)
def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return jnp.left_shift(x, y)


@register_op(differentiable=False)
def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    return jnp.right_shift(x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    from .registry import call_op
    return call_op("allclose",
                   lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan),
                   (x, y), {})


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    from .registry import call_op
    return call_op("isclose",
                   lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                            equal_nan=equal_nan),
                   (x, y), {})


def equal_all(x, y, name=None):
    from .registry import call_op
    return call_op("equal_all", lambda a, b: jnp.array_equal(a, b), (x, y), {})


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def is_empty(x) -> Tensor:
    return Tensor(jnp.asarray(x.size == 0))


@register_op(differentiable=False)
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return jnp.isin(x, test_x, invert=invert)

"""Op registry + eager dispatch.

TPU-native redesign of the reference's op machinery: where the reference has
a YAML corpus (paddle/phi/ops/yaml/ops.yaml) + codegen emitting C++ dispatch
(paddle/phi/api/generator/api_gen.py) + KernelFactory selection
(paddle/phi/core/kernel_factory.h:326), here every op is one pure-JAX
function registered with metadata. "Kernel selection" is XLA's job: the same
registered function serves eager (dispatched per-op with a tape record) and
captured/compiled execution (traced under jax.jit into one HLO module).

Dispatch per eager call:
  1. unwrap Tensor args -> jax arrays
  2. if grads needed: jax.vjp over a closure treating non-differentiable args
     as constants; record a GradNode on the tape
  3. wrap outputs back into Tensors carrying the node link

The registry doubles as the source for installing Tensor methods (the
reference's monkey_patch_tensor) and the `_C_ops`-style flat namespace.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.flags import get_flag
from ..core.tensor import Tensor
from ..autograd import tape as _tape


class OpDef:
    __slots__ = ("name", "fn", "differentiable", "method_name", "wrapper")

    def __init__(self, name, fn, differentiable, method_name, wrapper):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.method_name = method_name
        self.wrapper = wrapper


OPS: Dict[str, OpDef] = {}
_PENDING_METHODS: Dict[str, Callable] = {}


def _is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def _map_structure(fn, obj):
    """Map over Tensors nested at most one container deep (list/tuple of
    tensors, e.g. concat's input). Dicts are not op inputs in this API."""
    if isinstance(obj, Tensor):
        return fn(obj)
    if isinstance(obj, (list, tuple)) and any(isinstance(e, Tensor) for e in obj):
        return type(obj)(fn(e) if isinstance(e, Tensor) else e for e in obj)
    return obj


def _collect_tensors(args, kwargs):
    out = []

    def visit(obj):
        if isinstance(obj, Tensor):
            out.append(obj)
        elif isinstance(obj, (list, tuple)):
            for e in obj:
                if isinstance(e, Tensor):
                    out.append(e)

    for a in args:
        visit(a)
    for v in kwargs.values():
        visit(v)
    return out


def call_op(name: str, fn: Callable, args: tuple, kwargs: dict,
            differentiable: bool = True):
    """Eager-dispatch `fn` (pure JAX) over possibly-Tensor args."""
    tensors = _collect_tensors(args, kwargs)
    need_grad = (differentiable and _tape.grad_enabled()
                 and any(not t.stop_gradient or t._node is not None
                         for t in tensors))

    if not need_grad:
        uw_args = tuple(_map_structure(lambda t: t._data, a) for a in args)
        uw_kwargs = {k: _map_structure(lambda t: t._data, v)
                     for k, v in kwargs.items()}
        out = fn(*uw_args, **uw_kwargs)
        return _wrap_outputs(name, out, node=None)

    # Differentiable path: inputs needing grad become vjp primals, the rest
    # are closed over as constants.
    diff = [t for t in tensors if not t.stop_gradient or t._node is not None]
    diff_ids = {id(t): i for i, t in enumerate(diff)}

    def pure(*primals):
        def sub(t):
            i = diff_ids.get(id(t))
            return primals[i] if i is not None else t._data

        a = tuple(_map_structure(sub, x) for x in args)
        k = {kk: _map_structure(sub, v) for kk, v in kwargs.items()}
        return fn(*a, **k)

    primals = [t._data for t in diff]
    out, vjp_fn = jax.vjp(pure, *primals)

    flat, treedef = jax.tree_util.tree_flatten(out)
    avals = [(o.shape, o.dtype) for o in flat]
    node = _tape.GradNode(name, vjp_fn, diff, avals, treedef)
    return _wrap_outputs(name, out, node=node)


def _wrap_outputs(name: str, out, node):
    if get_flag("check_nan_inf"):
        _check_nan_inf(name, out)
    flat, treedef = jax.tree_util.tree_flatten(out)
    wrapped = []
    for i, arr in enumerate(flat):
        t = Tensor(arr, stop_gradient=(node is None))
        if node is not None:
            t._node = node
            t._out_index = i
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(treedef, wrapped)


def _check_nan_inf(name, out):
    import numpy as np
    for arr in jax.tree_util.tree_leaves(out):
        if jnp.issubdtype(arr.dtype, jnp.floating) and not isinstance(
                arr, jax.core.Tracer):
            if not bool(jnp.isfinite(arr).all()):
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op '{name}' "
                    "(FLAGS_check_nan_inf is on)")


def register_op(name: Optional[str] = None, *, differentiable: bool = True,
                method: Optional[str] = None, also_method: bool = True):
    """Decorator: register a pure-JAX function as a framework op.

    The decorated function receives raw jax arrays (Tensors are unwrapped);
    its wrapper accepts Tensors/arrays/scalars and returns Tensors.
    `method`: name under which to install on Tensor (defaults to op name).
    """

    def deco(fn):
        op_name = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if get_flag("eager_log_ops"):
                print(f"[paddle_tpu op] {op_name}")
            return call_op(op_name, fn, args, kwargs, differentiable)

        opdef = OpDef(op_name, fn, differentiable, method or op_name, wrapper)
        OPS[op_name] = opdef
        if also_method:
            _PENDING_METHODS[opdef.method_name] = wrapper
        return wrapper

    return deco


def install_tensor_methods(extra: Optional[Dict[str, Callable]] = None):
    """Attach registered ops as Tensor methods (the reference's
    monkey_patch_tensor, python/paddle/base/dygraph/tensor_patch_methods.py)."""
    for mname, fn in _PENDING_METHODS.items():
        if not hasattr(Tensor, mname):
            setattr(Tensor, mname, fn)
    if extra:
        for mname, fn in extra.items():
            setattr(Tensor, mname, fn)


def get_op(name: str) -> OpDef:
    return OPS[name]

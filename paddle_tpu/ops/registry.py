"""Op registry + eager dispatch.

TPU-native redesign of the reference's op machinery: where the reference has
a YAML corpus (paddle/phi/ops/yaml/ops.yaml) + codegen emitting C++ dispatch
(paddle/phi/api/generator/api_gen.py) + KernelFactory selection
(paddle/phi/core/kernel_factory.h:326), here every op is one pure-JAX
function registered with metadata. "Kernel selection" is XLA's job: the same
registered function serves eager (dispatched per-op with a tape record) and
captured/compiled execution (traced under jax.jit into one HLO module).

Dispatch per eager call:
  1. unwrap Tensor args -> jax arrays
  2. if grads needed: jax.vjp over a closure treating non-differentiable args
     as constants; record a GradNode on the tape
  3. wrap outputs back into Tensors carrying the node link

The registry doubles as the source for installing Tensor methods (the
reference's monkey_patch_tensor) and the `_C_ops`-style flat namespace.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.flags import get_flag
from ..core.tensor import Tensor
from ..autograd import tape as _tape


class OpDef:
    __slots__ = ("name", "fn", "differentiable", "method_name", "wrapper")

    def __init__(self, name, fn, differentiable, method_name, wrapper):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.method_name = method_name
        self.wrapper = wrapper


OPS: Dict[str, OpDef] = {}
_PENDING_METHODS: Dict[str, Callable] = {}


def _is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def _map_structure(fn, obj):
    """Map over Tensors nested at most one container deep (list/tuple of
    tensors, e.g. concat's input). Dicts are not op inputs in this API."""
    if isinstance(obj, Tensor):
        return fn(obj)
    if isinstance(obj, (list, tuple)) and any(isinstance(e, Tensor) for e in obj):
        return type(obj)(fn(e) if isinstance(e, Tensor) else e for e in obj)
    return obj


def _collect_tensors(args, kwargs):
    out = []

    def visit(obj):
        if isinstance(obj, Tensor):
            out.append(obj)
        elif isinstance(obj, (list, tuple)):
            for e in obj:
                if isinstance(e, Tensor):
                    out.append(e)

    for a in args:
        visit(a)
    for v in kwargs.values():
        visit(v)
    return out


# ---------------------------------------------------------------------------
# eager vjp cache
#
# A fresh jax.vjp trace per eager op call costs hundreds of µs of pure
# Python/tracing overhead (the reference's entire L3/L4 C++ design exists
# to dodge the analogous cost). Caching key: (op, call structure, avals
# of every tensor leaf, static leaf values). Hit => dispatch goes through
# pre-jitted fwd/bwd callables whose own tracing happened once; the bwd
# re-runs the (tiny, eager-sized) forward inside to rebuild residuals —
# per-op remat, which is cheaper than per-call retracing for every eager
# workload we measured (tools/eager_bench.py, docs/PERF.md).
# ---------------------------------------------------------------------------

_VJP_CACHE: Dict = {}
_VJP_SEEN: set = set()
_VJP_UNCACHABLE: set = set()  # op names whose fns cannot be jitted
_VJP_CACHE_MAX = 4096
# active partial-graph recorder (jit/segments.py sets/clears this; kept
# here so the hot dispatch path reads one module global, no import)
_ACTIVE_SEGMENT = None
# op-level trace callback (onnx/export.py graph capture): called with
# (name, args, kwargs, wrapped_out) on the no-grad dispatch path
_ONNX_TRACE = None


def _flatten_call(args, kwargs):
    """Flatten (args, kwargs) into (treedef, tensor_leaves, static_leaves,
    tensor_positions). Tensors are leaves; everything else is a static
    leaf keyed by value."""
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=_is_tensor)
    tensor_pos = [i for i, l in enumerate(leaves) if _is_tensor(l)]
    tensors = [leaves[i] for i in tensor_pos]
    statics = tuple(l for l in leaves if not _is_tensor(l))
    return treedef, leaves, tensors, statics, tuple(tensor_pos)


def _cache_key(name, fn, treedef, tensors, diff_mask, statics, tensor_pos):
    """The key INCLUDES fn's identity: some APIs build a fresh closure
    per call (dropout's PRNG key, interpolate's size, the create_graph
    grad[...] closures) — keying on the name alone would replay the
    first call's baked-in constants on every hit."""
    try:
        avals = tuple((t._data.shape, str(t._data.dtype)) for t in tensors)
        return (name, fn, treedef, avals, diff_mask, statics, tensor_pos,
                hash(statics))
    except TypeError:
        return None  # unhashable static arg: fall back to uncached path


def _build_cached(name, fn, treedef, leaves_template, tensor_pos,
                  diff_mask):
    """Build jitted fwd / bwd for one (structure, avals, statics) class."""

    def rebuild(tensor_arrays):
        leaves = list(leaves_template)
        for p, arr in zip(tensor_pos, tensor_arrays):
            leaves[p] = arr
        args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
        return fn(*args, **kwargs)

    def fwd(tensor_arrays):
        return rebuild(tensor_arrays)

    def bwd(tensor_arrays, cot_tree):
        def pure(*diff_arrays):
            it = iter(diff_arrays)
            full = [next(it) if d else a
                    for d, a in zip(diff_mask, tensor_arrays)]
            return rebuild(full)

        primals = [a for d, a in zip(diff_mask, tensor_arrays) if d]
        _, vjp_fn = jax.vjp(pure, *primals)
        return vjp_fn(cot_tree)

    return jax.jit(fwd), jax.jit(bwd)


def _call_op_cached(name, fn, args, kwargs, diff, tensors):
    treedef, leaves, tensors2, statics, tensor_pos = _flatten_call(
        args, kwargs)
    diff_ids = {id(t) for t in diff}
    diff_mask = tuple(id(t) in diff_ids for t in tensors2)
    key = _cache_key(name, fn, treedef, tensors2, diff_mask, statics,
                     tensor_pos)
    if key is None:
        return None
    entry = _VJP_CACHE.get(key)
    if entry is None:
        # build only on the SECOND occurrence of a key: per-call closure
        # fns (fresh object every call) then never trigger a build, and
        # stable keys amortise theirs from call 2 on
        if key not in _VJP_SEEN:
            if len(_VJP_SEEN) > _VJP_CACHE_MAX:
                _VJP_SEEN.clear()
            _VJP_SEEN.add(key)
            return None
        if len(_VJP_CACHE) > _VJP_CACHE_MAX:
            _VJP_CACHE.clear()
        # template: static leaves keep their values; tensor slots are
        # None placeholders (storing first-call arrays would pin those
        # device buffers for the cache entry's lifetime) — every tensor
        # slot is overwritten by rebuild() before use
        template = [None if _is_tensor(l) else l for l in leaves]
        entry = _build_cached(name, fn, treedef, template, tensor_pos,
                              diff_mask)
        _VJP_CACHE[key] = entry
    fwd_jit, bwd_jit = entry
    arrays = [t._data for t in tensors2]
    try:
        out = fwd_jit(arrays)
    except Exception as e:
        _VJP_CACHE.pop(key, None)
        # only TRACE-structure failures (data-dependent output shapes:
        # masked_select, nonzero) poison the op name permanently;
        # ordinary user errors (bad shapes/dtypes) just fall back once —
        # the uncached path re-raises them — and must not disable the
        # cache for every later valid call of this op
        if isinstance(e, jax.errors.JAXTypeError):
            # key by (name, fn): shared wrapper names (every to_static
            # Layer dispatches as "to_static:forward") must not let one
            # untraceable model poison the cache for all the others
            _VJP_UNCACHABLE.add((name, fn))
        return None

    flat, treedef_out = jax.tree_util.tree_flatten(out)
    avals = [(o.shape, o.dtype) for o in flat]
    diff_list = [t for t, d in zip(tensors2, diff_mask) if d]

    def vjp_fn(cot_tree, _arrays=arrays):
        return bwd_jit(_arrays, cot_tree)

    def pure_fn(*diff_arrays, _arrays=arrays):
        it = iter(diff_arrays)
        full = [next(it) if d else a
                for d, a in zip(diff_mask, _arrays)]
        return fwd_jit(full)

    node = _tape.GradNode(name, vjp_fn, diff_list, avals, treedef_out,
                          pure_fn=pure_fn)
    return _wrap_outputs(name, out, node=node)


def call_op(name: str, fn: Callable, args: tuple, kwargs: dict,
            differentiable: bool = True):
    """Eager-dispatch `fn` (pure JAX) over possibly-Tensor args."""
    tensors = _collect_tensors(args, kwargs)
    need_grad = (differentiable and _tape.grad_enabled()
                 and any(not t.stop_gradient or t._node is not None
                         for t in tensors))

    if _ACTIVE_SEGMENT is not None:
        # partial-graph capture (jit/segments.py): record instead of
        # execute; None means "run eagerly" (the recorder flushed first)
        res = _ACTIVE_SEGMENT.record(name, fn, args, kwargs, need_grad)
        if res is not None:
            return res

    if not need_grad:
        uw_args = tuple(_map_structure(lambda t: t._data, a) for a in args)
        uw_kwargs = {k: _map_structure(lambda t: t._data, v)
                     for k, v in kwargs.items()}
        out = fn(*uw_args, **uw_kwargs)
        wrapped = _wrap_outputs(name, out, node=None)
        if _ONNX_TRACE is not None:
            _ONNX_TRACE(name, args, kwargs, wrapped)
        return wrapped

    diff = [t for t in tensors if not t.stop_gradient or t._node is not None]

    if get_flag("eager_vjp_cache") and (name, fn) not in _VJP_UNCACHABLE:
        try:
            res = _call_op_cached(name, fn, args, kwargs, diff, tensors)
        except (TypeError, ValueError):
            res = None  # untraceable structure: uncached fallback
        if res is not None:
            return res

    # Uncached path: inputs needing grad become vjp primals, the rest
    # are closed over as constants.
    diff_ids = {id(t): i for i, t in enumerate(diff)}

    def pure(*primals):
        def sub(t):
            i = diff_ids.get(id(t))
            return primals[i] if i is not None else t._data

        a = tuple(_map_structure(sub, x) for x in args)
        k = {kk: _map_structure(sub, v) for kk, v in kwargs.items()}
        return fn(*a, **k)

    primals = [t._data for t in diff]
    out, vjp_fn = jax.vjp(pure, *primals)

    flat, treedef = jax.tree_util.tree_flatten(out)
    avals = [(o.shape, o.dtype) for o in flat]
    node = _tape.GradNode(name, vjp_fn, diff, avals, treedef,
                          pure_fn=pure)
    return _wrap_outputs(name, out, node=node)


def _wrap_outputs(name: str, out, node):
    if get_flag("check_nan_inf"):
        _check_nan_inf(name, out)
    flat, treedef = jax.tree_util.tree_flatten(out)
    wrapped = []
    for i, arr in enumerate(flat):
        t = Tensor(arr, stop_gradient=(node is None))
        if node is not None:
            t._node = node
            t._out_index = i
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(treedef, wrapped)


def _check_nan_inf(name, out):
    import numpy as np
    for arr in jax.tree_util.tree_leaves(out):
        if jnp.issubdtype(arr.dtype, jnp.floating) and not isinstance(
                arr, jax.core.Tracer):
            if not bool(jnp.isfinite(arr).all()):  # noqa: PT003 — opt-in debug flag, sync is the feature
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op '{name}' "
                    "(FLAGS_check_nan_inf is on)")


def register_op(name: Optional[str] = None, *, differentiable: bool = True,
                method: Optional[str] = None, also_method: bool = True):
    """Decorator: register a pure-JAX function as a framework op.

    The decorated function receives raw jax arrays (Tensors are unwrapped);
    its wrapper accepts Tensors/arrays/scalars and returns Tensors.
    `method`: name under which to install on Tensor (defaults to op name).
    """

    def deco(fn):
        op_name = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if get_flag("eager_log_ops"):
                print(f"[paddle_tpu op] {op_name}")
            return call_op(op_name, fn, args, kwargs, differentiable)

        opdef = OpDef(op_name, fn, differentiable, method or op_name, wrapper)
        OPS[op_name] = opdef
        if also_method:
            _PENDING_METHODS[opdef.method_name] = wrapper
        return wrapper

    return deco


def install_tensor_methods(extra: Optional[Dict[str, Callable]] = None):
    """Attach registered ops as Tensor methods (the reference's
    monkey_patch_tensor, python/paddle/base/dygraph/tensor_patch_methods.py)."""
    for mname, fn in _PENDING_METHODS.items():
        if not hasattr(Tensor, mname):
            setattr(Tensor, mname, fn)
    if extra:
        for mname, fn in extra.items():
            setattr(Tensor, mname, fn)


def get_op(name: str) -> OpDef:
    return OPS[name]

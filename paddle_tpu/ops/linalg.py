"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, kernels via
cuBLAS/cuSOLVER in paddle/phi/kernels/funcs/blas). On TPU: matmul rides the
MXU; decompositions lower to XLA's linalg custom calls."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, call_op


@register_op()
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@register_op()
def mm(input, mat2, name=None):
    return jnp.matmul(input, mat2)


@register_op()
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@register_op()
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@register_op()
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@register_op()
def t(input, name=None):
    if input.ndim < 2:
        return input
    return jnp.swapaxes(input, -1, -2)


def einsum(equation, *operands):
    return call_op("einsum",
                   lambda *ops: jnp.einsum(equation, *ops),
                   operands, {})


@register_op()
def norm(x, p=None, axis=None, keepdim=False, name=None):
    if axis is None:
        # frobenius over all elements == 2-norm of the flattened vector
        x = x.reshape(-1)
        axis = 0
        p = 2 if p in (None, "fro") else p
    elif isinstance(axis, (list, tuple)):
        axis = tuple(axis)
        p = "fro" if p is None else p
    else:
        p = 2 if p is None else p
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@register_op()
def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@register_op()
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


@register_op()
def dist(x, y, p=2, name=None):
    return jnp.linalg.norm((x - y).reshape(-1), ord=p)


@register_op()
def cholesky(x, upper=False, name=None):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2).conj() if upper else l


@register_op()
def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@register_op()
def inverse(x, name=None):
    return jnp.linalg.inv(x)


@register_op()
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register_op(differentiable=False)
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op()
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@register_op()
def det(x, name=None):
    return jnp.linalg.det(x)


@register_op()
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@register_op()
def qr(x, mode="reduced", name=None):
    return tuple(jnp.linalg.qr(x, mode=mode))


@register_op()
def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2).conj()


@register_op()
def svdvals(x, name=None):
    return jnp.linalg.svd(x, compute_uv=False)


@register_op()
def eig(x, name=None):
    # XLA has no TPU eig; compute on CPU via callback in eager mode
    w, v = np.linalg.eig(np.asarray(x))
    return jnp.asarray(w), jnp.asarray(v)


@register_op()
def eigh(x, UPLO="L", name=None):
    return tuple(jnp.linalg.eigh(x, symmetrize_input=True))


@register_op()
def eigvals(x, name=None):
    return jnp.asarray(np.linalg.eigvals(np.asarray(x)))


@register_op()
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x)


@register_op()
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@register_op()
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register_op()
def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op()
def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    if get_infos:
        return lu_, piv.astype(jnp.int32) + 1, jnp.zeros((), jnp.int32)
    return lu_, piv.astype(jnp.int32) + 1


@register_op()
def matrix_exp(x, name=None):
    return jax.scipy.linalg.expm(x)


@register_op()
def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(list(x))


@register_op()
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@register_op()
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@register_op()
def histogram(input, bins=100, min=0, max=0, name=None):
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(input.reshape(-1), bins=bins, range=rng)
    return hist


@register_op()
def bincount(x, weights=None, minlength=0, name=None):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)


@register_op()
def cond(x, p=None, name=None):
    """Condition number (reference python/paddle/tensor/linalg.py cond)."""
    return jnp.linalg.cond(x, p=p)


@register_op()
def cholesky_inverse(x, upper=False, name=None):
    """Inverse from a Cholesky factor: (LL^T)^-1 via two triangular
    solves (reference cholesky_inverse; no dense inverse materialized
    beyond the solve)."""
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    li = jax.scipy.linalg.solve_triangular(x, eye, lower=not upper)
    return (li.T @ li) if not upper else (li @ li.T)


@register_op()
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Split packed LU + pivots into (P, L, U) (reference lu_unpack)."""
    n = x.shape[-2]
    L = jnp.tril(x, -1) + jnp.eye(n, x.shape[-1], dtype=x.dtype)
    L = L[..., :, :min(x.shape[-2], x.shape[-1])]
    U = jnp.triu(x)[..., :min(x.shape[-2], x.shape[-1]), :]
    # pivots (1-based sequential swaps) -> permutation matrix
    piv = y.astype(jnp.int32) - 1
    perm = jnp.arange(n)
    for i in range(piv.shape[-1]):
        j = piv[..., i]
        pi, pj = perm[i], perm[j]
        perm = perm.at[i].set(pj).at[j].set(pi)
    P = jnp.eye(n, dtype=x.dtype)[perm].T
    return P, L, U


def _householder_full(x, tau):
    """Full m x m Q = H_0 H_1 ... H_{k-1} from packed reflectors.
    Batched leading dims handled by vmapping the 2-D core."""
    if x.ndim > 2:
        return jax.vmap(_householder_full)(x, tau)
    m, n = x.shape[-2], x.shape[-1]
    Q = jnp.eye(m, dtype=x.dtype)
    for i in range(n):
        v = jnp.where(jnp.arange(m) < i, 0.0, x[:, i])
        v = v.at[i].set(1.0)
        H = jnp.eye(m, dtype=x.dtype) - tau[i] * jnp.outer(v, v)
        Q = Q @ H
    return Q


@register_op()
def householder_product(x, tau, name=None):
    """Q (thin, m x n) from Householder reflectors (reference
    householder_product / LAPACK orgqr)."""
    return _householder_full(x, tau)[..., :, :x.shape[-1]]


@register_op()
def ormqr(input, tau, other, left=True, transpose=False, name=None):
    """Multiply ``other`` by Q of a QR factorization (reference ormqr).
    Left-multiplication applies the FULL m x m Q (LAPACK ormqr
    semantics), not the thin factor."""
    Q = _householder_full(input, tau)
    Qm = jnp.swapaxes(Q, -2, -1) if transpose else Q
    return (Qm @ other) if left else (other @ Qm)


@register_op()
def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference svd_lowrank; Halko et al.):
    q-dim range finder + power iterations — all matmuls, MXU-friendly."""
    from ..core.generator import next_key
    m, n = x.shape[-2], x.shape[-1]
    q = min(q, m, n)
    a = x - M if M is not None else x
    omega = jax.random.normal(next_key(), (n, q), dtype=a.dtype)
    y = a @ omega
    for _ in range(niter):
        y = a @ (a.T @ y)
    Q, _ = jnp.linalg.qr(y)
    b = Q.T @ a
    u, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return Q @ u, s, vh.T


@register_op()
def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over svd_lowrank (reference pca_lowrank)."""
    m, n = x.shape[-2], x.shape[-1]
    q = min(6, m, n) if q is None else q
    a = x - x.mean(axis=-2, keepdims=True) if center else x
    return svd_lowrank.__wrapped__(a, q=q, niter=niter)

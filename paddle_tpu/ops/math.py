"""Elementwise and binary math ops (reference: python/paddle/tensor/math.py,
kernels in paddle/phi/kernels/{cpu,gpu}/*elementwise*, activation*).

Each op is one pure-JAX function; XLA fuses chains of these into single
TPU kernels, which replaces the reference's hand-fused CUDA elementwise
machinery (paddle/phi/kernels/funcs/elementwise_base.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from .registry import register_op


def _u(x):
    """Unwrap possible Tensor (for scalar positions already unwrapped by
    dispatch this is a no-op)."""
    return x


# -- binary ----------------------------------------------------------------

@register_op()
def add(x, y, name=None):
    return jnp.add(x, y)


@register_op()
def subtract(x, y, name=None):
    return jnp.subtract(x, y)


@register_op()
def multiply(x, y, name=None):
    return jnp.multiply(x, y)


@register_op()
def divide(x, y, name=None):
    return jnp.true_divide(x, y)


@register_op(differentiable=False)
def floor_divide(x, y, name=None):
    return jnp.floor_divide(x, y)


@register_op()
def remainder(x, y, name=None):
    return jnp.remainder(x, y)


mod = remainder
floor_mod = remainder


@register_op()
def pow(x, y, name=None):
    return jnp.power(x, y)


@register_op()
def maximum(x, y, name=None):
    return jnp.maximum(x, y)


@register_op()
def minimum(x, y, name=None):
    return jnp.minimum(x, y)


@register_op()
def fmax(x, y, name=None):
    return jnp.fmax(x, y)


@register_op()
def fmin(x, y, name=None):
    return jnp.fmin(x, y)


@register_op()
def atan2(x, y, name=None):
    return jnp.arctan2(x, y)


@register_op()
def hypot(x, y, name=None):
    return jnp.hypot(x, y)


@register_op()
def logaddexp(x, y, name=None):
    return jnp.logaddexp(x, y)


@register_op()
def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


@register_op(differentiable=False)  # jax defines no grad rule for it
def nextafter(x, y, name=None):
    return jnp.nextafter(x, y)


@register_op()
def copysign(x, y, name=None):
    return jnp.copysign(x, y)


@register_op(differentiable=False)
def gcd(x, y, name=None):
    return jnp.gcd(x, y)


@register_op(differentiable=False)
def lcm(x, y, name=None):
    return jnp.lcm(x, y)


# -- unary -----------------------------------------------------------------

@register_op()
def abs(x, name=None):
    return jnp.abs(x)


@register_op()
def neg(x, name=None):
    return jnp.negative(x)


@register_op()
def exp(x, name=None):
    return jnp.exp(x)


@register_op()
def expm1(x, name=None):
    return jnp.expm1(x)


@register_op()
def log(x, name=None):
    return jnp.log(x)


@register_op()
def log2(x, name=None):
    return jnp.log2(x)


@register_op()
def log10(x, name=None):
    return jnp.log10(x)


@register_op()
def log1p(x, name=None):
    return jnp.log1p(x)


@register_op()
def sqrt(x, name=None):
    return jnp.sqrt(x)


@register_op()
def rsqrt(x, name=None):
    return jax.lax.rsqrt(x)


@register_op()
def square(x, name=None):
    return jnp.square(x)


@register_op()
def reciprocal(x, name=None):
    return jnp.reciprocal(x)


@register_op()
def sin(x, name=None):
    return jnp.sin(x)


@register_op()
def cos(x, name=None):
    return jnp.cos(x)


@register_op()
def tan(x, name=None):
    return jnp.tan(x)


@register_op()
def asin(x, name=None):
    return jnp.arcsin(x)


@register_op()
def acos(x, name=None):
    return jnp.arccos(x)


@register_op()
def atan(x, name=None):
    return jnp.arctan(x)


@register_op()
def sinh(x, name=None):
    return jnp.sinh(x)


@register_op()
def cosh(x, name=None):
    return jnp.cosh(x)


@register_op()
def tanh(x, name=None):
    return jnp.tanh(x)


@register_op()
def asinh(x, name=None):
    return jnp.arcsinh(x)


@register_op()
def acosh(x, name=None):
    return jnp.arccosh(x)


@register_op()
def atanh(x, name=None):
    return jnp.arctanh(x)


@register_op()
def erf(x, name=None):
    return jax.scipy.special.erf(x)


@register_op()
def erfinv(x, name=None):
    return jax.scipy.special.erfinv(x)


@register_op()
def lgamma(x, name=None):
    return jax.scipy.special.gammaln(x)


@register_op()
def digamma(x, name=None):
    return jax.scipy.special.digamma(x)


@register_op()
def i0(x, name=None):
    return jax.scipy.special.i0(x)


@register_op()
def i0e(x, name=None):
    return jax.scipy.special.i0e(x)


@register_op()
def i1(x, name=None):
    return jax.scipy.special.i1(x)


@register_op()
def i1e(x, name=None):
    return jax.scipy.special.i1e(x)


@register_op(differentiable=False)
def floor(x, name=None):
    return jnp.floor(x)


@register_op(differentiable=False)
def ceil(x, name=None):
    return jnp.ceil(x)


@register_op(differentiable=False)
def round(x, decimals=0, name=None):
    return jnp.round(x, decimals)


@register_op(differentiable=False)
def trunc(x, name=None):
    return jnp.trunc(x)


@register_op(differentiable=False)
def frac(x, name=None):
    return x - jnp.trunc(x)


@register_op(differentiable=False)
def sign(x, name=None):
    return jnp.sign(x)


@register_op(differentiable=False)
def sgn(x, name=None):
    return jnp.sign(x)


@register_op()
def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, min, max)


@register_op()
def logit(x, eps=None, name=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@register_op()
def logcumsumexp(x, axis=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


@register_op()
def deg2rad(x, name=None):
    return jnp.deg2rad(x)


@register_op()
def rad2deg(x, name=None):
    return jnp.rad2deg(x)


@register_op()
def angle(x, name=None):
    return jnp.angle(x)


@register_op()
def conj(x, name=None):
    return jnp.conj(x)


@register_op()
def real(x, name=None):
    return jnp.real(x)


@register_op()
def imag(x, name=None):
    return jnp.imag(x)


@register_op()
def lerp(x, y, weight, name=None):
    return x + weight * (y - x)


@register_op()
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@register_op()
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    if act == "relu":
        out = jnp.maximum(out, 0)
    elif act == "tanh":
        out = jnp.tanh(out)
    return out


@register_op()
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@register_op()
def multiplex(inputs, index, name=None):
    stacked = jnp.stack(inputs, axis=0)  # (n, batch, ...)
    idx = index.reshape(-1)
    return jnp.take_along_axis(
        stacked,
        idx[(None, slice(None)) + (None,) * (stacked.ndim - 2)],
        axis=0)[0]


@register_op()
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * (x @ y)


@register_op()
def inner(x, y, name=None):
    return jnp.inner(x, y)


@register_op()
def outer(x, y, name=None):
    return jnp.outer(x, y)


@register_op()
def kron(x, y, name=None):
    return jnp.kron(x, y)


@register_op()
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


@register_op()
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op()
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op(differentiable=False)
def isnan(x, name=None):
    return jnp.isnan(x)


@register_op(differentiable=False)
def isinf(x, name=None):
    return jnp.isinf(x)


@register_op(differentiable=False)
def isfinite(x, name=None):
    return jnp.isfinite(x)


@register_op(differentiable=False)
def isneginf(x, name=None):
    return jnp.isneginf(x)


@register_op(differentiable=False)
def isposinf(x, name=None):
    return jnp.isposinf(x)


@register_op(differentiable=False)
def isreal(x, name=None):
    return jnp.isreal(x)


@register_op()
def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, x)


@register_op()
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@register_op()
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@register_op()
def softsign(x, name=None):
    return jax.nn.soft_sign(x)


@register_op()
def ldexp(x, y, name=None):
    return jnp.ldexp(x, y)


@register_op()
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@register_op()
def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummax(x, axis=axis)
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    is_new = x >= vals
    ind = jax.lax.cummax(jnp.where(is_new, idx, 0), axis=axis)
    return vals, ind.astype(dtypes.to_jax_dtype(dtype))


@register_op()
def cummin(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummin(x, axis=axis)
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    is_new = x <= vals
    ind = jax.lax.cummax(jnp.where(is_new, idx, 0), axis=axis)
    return vals, ind.astype(dtypes.to_jax_dtype(dtype))

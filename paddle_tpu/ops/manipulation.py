"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py;
view kernels in paddle/phi/kernels/stride/). On TPU these are metadata-only or
single relayout HLOs — XLA handles copy elision, so there is no view/stride
machinery to replicate."""
from __future__ import annotations


import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .registry import register_op, call_op

# the paddle-parity op below is named `slice`, shadowing the builtin for
# the rest of this module — keep a handle to the real one
_pyslice = slice


@register_op()
def reshape(x, shape, name=None):
    if isinstance(shape, jax.Array) or isinstance(shape, np.ndarray):
        shape = [int(s) for s in np.asarray(shape)]
    shape = tuple(int(s) for s in shape)
    return jnp.reshape(x, shape)


@register_op()
def transpose(x, perm=None, name=None):
    return jnp.transpose(x, axes=perm)


@register_op()
def squeeze(x, axis=None, name=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


@register_op()
def unsqueeze(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, axis)


@register_op()
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = (x.shape[:start] + (-1,) + x.shape[stop + 1:])
    return jnp.reshape(x, new_shape)


@register_op()
def concat(x, axis=0, name=None):
    if isinstance(axis, jax.Array):
        axis = int(axis)
    return jnp.concatenate(list(x), axis=axis)


@register_op()
def stack(x, axis=0, name=None):
    return jnp.stack(list(x), axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    axis = int(axis)
    if isinstance(num_or_sections, int):
        outs_spec = num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        total = arr.shape[axis]
        if any(s == -1 for s in sections):
            rest = total - builtins_sum(s for s in sections if s != -1)
            sections = [rest if s == -1 else s for s in sections]
        outs_spec = np.cumsum(sections)[:-1].tolist()
    return call_op("split",
                   lambda a: tuple(jnp.split(a, outs_spec, axis=axis)),
                   (x,), {})


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    parts = split(x, n, axis)
    from . import manipulation as m
    return [squeeze(p, axis=axis) for p in parts]


@register_op()
def tile(x, repeat_times, name=None):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@register_op()
def expand(x, shape, name=None):
    shape = tuple(int(s) for s in shape)
    # paddle allows -1 meaning keep dim
    full = []
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x.shape[i - offset])
        else:
            full.append(s)
    return jnp.broadcast_to(x, tuple(full))


@register_op()
def expand_as(x, y, name=None):
    return jnp.broadcast_to(x, y.shape)


@register_op()
def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, tuple(int(s) for s in shape))


def broadcast_tensors(inputs, name=None):
    return call_op("broadcast_tensors",
                   lambda xs: tuple(jnp.broadcast_arrays(*xs)),
                   (list(inputs),), {})


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@register_op()
def flip(x, axis, name=None):
    return jnp.flip(x, axis=axis if not isinstance(axis, list) else tuple(axis))


@register_op()
def roll(x, shifts, axis=None, name=None):
    return jnp.roll(x, shifts, axis=tuple(axis) if isinstance(axis, list) else axis)


@register_op()
def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op()
def gather(x, index, axis=0, name=None):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)


@register_op()
def gather_nd(x, index, name=None):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@register_op()
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return jnp.take_along_axis(arr, indices, axis=axis)


@register_op()
def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape) \
        if np.ndim(values) == 0 else jnp.asarray(values, arr.dtype)
    dims = list(range(arr.ndim))
    # build index grid: along `axis` use `indices`, elsewhere iota
    grids = []
    for d in dims:
        if d == axis:
            grids.append(indices)
        else:
            g = jnp.arange(indices.shape[d]).reshape(
                [indices.shape[d] if i == d else 1 for i in dims])
            grids.append(jnp.broadcast_to(g, indices.shape))
    idx = tuple(grids)
    at = arr.at[idx]
    if reduce == "assign":
        return at.set(values)
    if reduce in ("add", "sum"):
        return at.add(values)
    if reduce in ("mul", "multiply"):
        return at.multiply(values)
    if reduce == "amax":
        return at.max(values)
    if reduce == "amin":
        return at.min(values)
    raise ValueError(f"unknown reduce: {reduce}")


@register_op()
def scatter(x, index, updates, overwrite=True, name=None):
    if overwrite:
        return x.at[index].set(updates)
    # paddle semantics: non-overwrite means zero-then-add for duplicates
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@register_op()
def scatter_nd_add(x, index, updates, name=None):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape, name=None):
    from . import creation
    z = creation.zeros(shape, dtype=updates.dtype.name if isinstance(updates, Tensor) else None)
    return scatter_nd_add(z, index, updates)


@register_op()
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, index, axis=axis)


@register_op()
def index_sample(x, index, name=None):
    return jnp.take_along_axis(x, index, axis=1)


@register_op()
def index_add(x, index, axis, value, name=None):
    sl = [_pyslice(None)] * x.ndim
    sl[axis] = index
    return x.at[tuple(sl)].add(value)


@register_op()
def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


@register_op()
def masked_select(x, mask, name=None):
    # data-dependent shape: returns compacted values (eager only; inside jit
    # use masked_fill/where which keep static shapes, the TPU-friendly path)
    return x[mask]


@register_op()
def masked_fill(x, mask, value, name=None):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@register_op()
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return jnp.nonzero(condition)
    return jnp.where(condition, x, y)


@register_op(differentiable=False)
def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return tuple(nz)
    return jnp.stack(nz, axis=1)


@register_op(differentiable=False)
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(k)
    if largest:
        if axis in (-1, x.ndim - 1):
            vals, idx = jax.lax.top_k(x, k)
        else:
            xm = jnp.moveaxis(x, axis, -1)
            vals, idx = jax.lax.top_k(xm, k)
            vals = jnp.moveaxis(vals, -1, axis)
            idx = jnp.moveaxis(idx, -1, axis)
    else:
        xm = jnp.moveaxis(-x, axis, -1)
        v, idx = jax.lax.top_k(xm, k)
        vals = jnp.moveaxis(-v, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int32)


@register_op()
def sort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.sort(x, axis=axis, stable=stable or True)
    return jnp.flip(out, axis=axis) if descending else out


@register_op(differentiable=False)
def argsort(x, axis=-1, descending=False, stable=False, name=None):
    if descending:
        idx = jnp.argsort(-x, axis=axis, stable=True)
        return idx.astype(jnp.int32)
    return jnp.argsort(x, axis=axis, stable=True).astype(jnp.int32)


@register_op(differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        out = out.reshape(values.shape)
    return out.astype(jnp.int32)


@register_op(differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    out = jnp.searchsorted(sorted_sequence, x, side="right" if right else "left")
    return out.astype(jnp.int32)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # data-dependent shape -> eager/host computation (matches reference note
    # that dynamic-shape ops fall outside the compiled region on TPU)
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return outs[0] if len(outs) == 1 else tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    if axis is None:
        flat = arr.reshape(-1)
    else:
        flat = arr
    mask = np.ones(len(flat), dtype=bool)
    mask[1:] = flat[1:] != flat[:-1]
    out = [Tensor(jnp.asarray(flat[mask]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(mask) - 1)))
    if return_counts:
        idx = np.nonzero(mask)[0]
        counts = np.diff(np.append(idx, len(flat)))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


@register_op()
def cast(x, dtype, name=None):
    return x.astype(dtypes.to_jax_dtype(dtype))


@register_op()
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW",
        pad_from_left_axis=True, name=None):
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) % 2:
        raise ValueError(
            f"pad length must be even (lo/hi pairs), got {len(pad)}")
    if len(pad) > 2 * nd:
        raise ValueError(
            f"pad specifies {len(pad) // 2} dims but input has only {nd}")
    if len(pad) == 2 * nd:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        if not pad_from_left_axis:  # spec runs from the last axis backwards
            pairs = pairs[::-1]
    else:
        # partial spec pads spatial dims from the LAST dim backwards
        # (paddle/torch convention: [w_lo, w_hi, h_lo, h_hi, ...])
        k = len(pad) // 2
        pairs = [(0, 0)] * nd
        spatial = (list(range(1, nd - 1)) if data_format.endswith("C")
                   else list(range(2, nd)))  # NHWC vs NCHW layouts
        if len(spatial) < k:  # low-rank input: pad the last k dims
            spatial = list(range(nd - k, nd))
        for i in range(k):
            pairs[spatial[-1 - i]] = (pad[2 * i], pad[2 * i + 1])
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    if mode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=value)
    return jnp.pad(x, pairs, mode=mode_map[mode])


@register_op()
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


@register_op()
def swapaxes(x, axis0, axis1, name=None):
    return jnp.swapaxes(x, axis0, axis1)


@register_op()
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op()
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1])


@register_op()
def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op()
def crop(x, shape=None, offsets=None, name=None):
    shape = [x.shape[i] if s == -1 else int(s) for i, s in enumerate(shape)]
    offsets = [0] * x.ndim if offsets is None else [int(o) for o in offsets]
    sl = tuple(_pyslice(o, o + s) for o, s in zip(offsets, shape))
    return x[sl]


@register_op()
def slice(x, axes, starts, ends, name=None):
    sl = [_pyslice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = _pyslice(int(st), int(en))
    return x[tuple(sl)]


@register_op()
def strided_slice(x, axes, starts, ends, strides, name=None):
    sl = [_pyslice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        sl[ax] = _pyslice(int(st), int(en), int(sd))
    return x[tuple(sl)]


@register_op()
def tensordot(x, y, axes=2, name=None):
    return jnp.tensordot(x, y, axes=axes)


@register_op()
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    # im2col (N, C, H, W) -> (N, C*kh*kw, L)
    if isinstance(kernel_sizes, int):
        kernel_sizes = [kernel_sizes, kernel_sizes]
    if isinstance(strides, int):
        strides = [strides, strides]
    if isinstance(paddings, int):
        paddings = [paddings] * 4
    elif len(paddings) == 2:
        paddings = [paddings[0], paddings[1], paddings[0], paddings[1]]
    if isinstance(dilations, int):
        dilations = [dilations, dilations]
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (paddings[0], paddings[2]),
                    (paddings[1], paddings[3])))
    kh, kw = kernel_sizes
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=tuple(strides),
        padding="VALID", rhs_dilation=tuple(dilations),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    l = patches.shape[2] * patches.shape[3]
    return patches.reshape(n, c * kh * kw, l)


def atleast_1d(*inputs, name=None):
    outs = [call_op("atleast_1d", jnp.atleast_1d, (t,), {}) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [call_op("atleast_2d", jnp.atleast_2d, (t,), {}) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [call_op("atleast_3d", jnp.atleast_3d, (t,), {}) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(inp):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        hi = lo + shard_size
        in_shard = (inp >= lo) & (inp < hi)
        return jnp.where(in_shard, inp - lo, ignore_value)
    return call_op("shard_index", fn, (input,), {})

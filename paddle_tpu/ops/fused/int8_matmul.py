"""Weight-only int8 matmul: the decode-path dequant-in-matmul primitive.

Reference capability: the PTQ-deploy path (python/paddle/quantization/ +
the cutlass int8 weight-only GEMMs behind paddle.incubate's
weight_only_linear). Decode on TPU is weight-bandwidth-bound — a ~1.7B
bf16 model streams ~3.4 GB of weights per token against v5e's ~819 GB/s
HBM, a ~240 steps/s ceiling — so storing the projection weights as int8
(+ one f32 scale per output channel) halves the dominant byte stream.
Activations stay in the model dtype for the MXU; the dequant
(``q.astype(dtype) * scale``) is fused by XLA into the matmul operand,
never materialised at weight size in the jnp path.

``Int8Weight`` is a registered pytree, so quantized params flow through
``jax.jit``, ``lax.scan`` over stacked layer weights (both leaves carry
the leading L axis), and donation exactly like dense weights.

Two matmul implementations:
  * jnp (default): ``(x @ q.astype(x.dtype)) * scale`` — int8 values up
    to ±127 are exact in bf16, and applying the per-output-channel scale
    AFTER the matmul is O(out) instead of O(in·out).
  * pallas: the authored int8×bf16 kernel (ops/pallas/int8_matmul.py),
    opt-in via ``impl="pallas"`` / ``PADDLE_TPU_INT8_IMPL=pallas`` —
    ``"auto"`` stays on the jnp path until an on-chip A/B shows XLA's
    fusion leaving throughput on the table (docs/PERF.md decode notes).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

__all__ = ["Int8Weight", "quantize_weight_per_channel",
           "int8_weight_matmul", "fused_impl"]


def _default_impl() -> str:
    return os.environ.get("PADDLE_TPU_INT8_IMPL", "auto")


def fused_impl() -> str:
    """The FUSED implementation the current environment selects:
    ``"pallas"`` when ``PADDLE_TPU_INT8_IMPL=pallas``, else ``"jnp"``.
    The int8-epilogue rewrite pass (analysis/rewrite.py) resolves its
    replacement through this so a rewrite can never route back to the
    ``"unfused"`` baseline it is replacing (which would make the
    rewriter non-idempotent)."""
    return "pallas" if _default_impl() == "pallas" else "jnp"


def quantize_weight_per_channel(w):
    """Symmetric per-output-channel int8 quantization of a ``[..., in,
    out]`` weight (stacked leading axes — layer, expert — quantize
    independently per (leading..., out) channel, matching the
    reference's channel_wise_abs_max weight observer).

    Returns ``(q int8 [..., in, out], scale f32 [..., out])`` with
    ``w ≈ q * scale`` (scale = absmax/127, so dequant is one multiply).
    """
    wf = jnp.asarray(w).astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2), 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def int8_weight_matmul(x, q, scale, impl: str = "auto"):
    """``x [..., in] @ dequant(q [in, out], scale [out]) -> [..., out]``
    in ``x.dtype``. ``impl``: "auto"/"jnp" (XLA fuses the dequant into
    the matmul operand), "pallas" (authored kernel; interpret mode
    off-TPU), or "unfused" — dequantize the FULL dense weight first and
    matmul against it. The unfused form is the naive idiom the
    int8-epilogue rewrite pass exists to eliminate (and the baseline of
    the decode_profile rewrite A/B): it materialises the O(in*out)
    dequant product the fused forms never pay for."""
    resolved = _default_impl() if impl == "auto" else impl
    if resolved == "pallas":
        from ..pallas.int8_matmul import int8_matmul_pallas
        return int8_matmul_pallas(x, q, scale)
    if resolved == "unfused":
        w = (q.astype(jnp.float32)
             * scale[..., None, :]).astype(x.dtype)
        return jnp.matmul(x, w)
    out = jnp.matmul(x, q.astype(x.dtype)) * scale.astype(jnp.float32)
    return out.astype(x.dtype)


@jax.tree_util.register_pytree_node_class
class Int8Weight:
    """A weight-only-quantized matmul operand: ``q`` int8 ``[..., in,
    out]`` + ``scale`` f32 ``[..., out]``. Model code calls
    ``w.dequant_matmul(x)`` (or ``w.dequant()`` where a dense tensor is
    unavoidable, e.g. einsum-dispatched MoE experts — XLA fuses the cast
    there too); everything else (scan unstacking, jit, device_put) treats
    it as a plain two-leaf pytree."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    # -- pytree protocol --
    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- array-ish surface --
    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def ndim(self):
        return self.q.ndim

    def __repr__(self):
        return (f"Int8Weight(q={getattr(self.q, 'shape', None)}, "
                f"scale={getattr(self.scale, 'shape', None)})")

    # -- ops --
    @classmethod
    def quantize(cls, w) -> "Int8Weight":
        return cls(*quantize_weight_per_channel(w))

    def dequant(self, dtype=jnp.bfloat16):
        """Dense ``[..., in, out]`` approximation in ``dtype``."""
        return (self.q.astype(jnp.float32)
                * self.scale[..., None, :]).astype(dtype)

    def dequant_matmul(self, x, impl: str = "auto"):
        return int8_weight_matmul(x, self.q, self.scale, impl=impl)

"""Conv + folded-BN + activation: the replacement surface of the
ResNet rewrite passes (analysis/rewrite_conv.py).

Reference capability: the conv_bn_fuse / conv_elementwise_add_act IR
passes (paddle/fluid/framework/ir/) that PaddlePaddle applies to every
deployed CNN. Here the fold happens at the jaxpr level — the rewrite
pass matches ``conv → batch_norm(infer) → relu`` and substitutes this
module's entry points, which:

* fold the BN affine into the conv weights per output channel
  (``s = gamma·rsqrt(var+eps); w' = w·s; bias = beta − mean·s`` —
  O(C·k·k) arithmetic instead of three extra HBM round-trips over the
  activation);
* normalise layout to NHWC (channels-last is the TPU-native conv
  layout; the rewrite keeps NCHW only at the matched region's border);
* route 1×1/stride-1 convolutions — 36 of ResNet-50's 53 convs —
  through the authored matmul+bias+relu epilogue kernel
  (ops/pallas/conv_epilogue.py) when ``PADDLE_TPU_CONV_EPILOGUE_IMPL=
  pallas``, the same ``fused_impl()`` discipline as int8_matmul (a
  rewrite must never resolve back to the baseline it replaced);
* space-to-depth the 7×7/stride-2 stem: the input's 2×2 phases move
  into channels (3 → 12) so the conv becomes a dense 4×4/stride-1 conv
  at 112×112 — the stem stops being the one sparse, misaligned conv in
  the network (`stem_s2d_conv`).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_impl", "conv_bias_act", "conv_bn_act_nchw",
           "space_to_depth_nhwc", "space_to_depth_stem_kernel",
           "stem_s2d_conv_nchw", "decode_precision"]


def _default_impl() -> str:
    return os.environ.get("PADDLE_TPU_CONV_EPILOGUE_IMPL", "auto")


def fused_impl() -> str:
    """The FUSED implementation the environment selects — ``"pallas"``
    under ``PADDLE_TPU_CONV_EPILOGUE_IMPL=pallas``, else ``"jnp"``.
    The conv-bn-fold rewrite resolves its replacement through this so
    it can never route back to the unfused conv→BN→relu baseline."""
    return "pallas" if _default_impl() == "pallas" else "jnp"


def _is_rowwise_matmul(w_hwio, strides, padding, dilation, groups) -> bool:
    kh, kw = w_hwio.shape[0], w_hwio.shape[1]
    return (kh == 1 and kw == 1 and tuple(strides) == (1, 1)
            and all(p == (0, 0) for p in padding)
            and tuple(dilation) == (1, 1) and groups == 1)


def decode_precision(precision):
    """The rewrite passes stash a matched conv's precision request as
    None or a pair of ``lax.Precision`` names (strings serialize into
    match statics); decode back to what lax accepts."""
    if precision is None:
        return None
    return tuple(lax.Precision[p] if isinstance(p, str) else p
                 for p in precision)


def _precision_is_default(precision) -> bool:
    decoded = decode_precision(precision)
    return decoded is None or all(p == lax.Precision.DEFAULT
                                  for p in decoded)


def conv_bias_act(x, w, bias, *, strides=(1, 1),
                  padding=((0, 0), (0, 0)), dilation=(1, 1),
                  groups=1, relu=True, impl="auto", precision=None):
    """NHWC conv + bias + optional relu in one fused surface.

    ``x`` [B,H,W,Cin] NHWC, ``w`` [kh,kw,Cin/groups,Cout] HWIO,
    ``bias`` [Cout]. 1×1/stride-1/ungrouped shapes dispatch to the
    Pallas epilogue kernel under ``impl="pallas"`` (only when the
    caller asked for default precision — the kernel's MXU passes don't
    honour HIGHEST); everything else is the jnp formulation (one
    conv_general_dilated + vector epilogue, which XLA fuses)."""
    resolved = _default_impl() if impl == "auto" else impl
    if (resolved == "pallas" and _precision_is_default(precision)
            and _is_rowwise_matmul(w, strides, padding, dilation, groups)):
        from ..pallas.conv_epilogue import matmul_bias_act
        b, h, wd, cin = x.shape
        cout = w.shape[-1]
        out = matmul_bias_act(x.reshape(b * h * wd, cin),
                              w.reshape(cin, cout), bias, relu=relu)
        return out.reshape(b, h, wd, cout)
    out = lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=tuple(strides),
        padding=tuple(padding), rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
        precision=decode_precision(precision))
    out = out + bias.astype(out.dtype)
    if relu:
        out = jax.nn.relu(out)
    return out


# ---------------------------------------------------------------------------
# space-to-depth stem (7x7/stride-2 -> dense 4x4/stride-1 at 4x channels)
# ---------------------------------------------------------------------------

def space_to_depth_nhwc(x):
    """[B,H,W,C] -> [B,H/2,W/2,4C]: each output pixel stacks its 2x2
    input phase block into channels (channel order (h2, w2, c))."""
    b, h, w, c = x.shape
    xs = x.reshape(b, h // 2, 2, w // 2, 2, c)
    return xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)


def space_to_depth_stem_kernel(w_hwio):
    """[7,7,Cin,Cout] HWIO -> the [4,4,4Cin,Cout] kernel that, applied
    stride-1 with padding ((2,1),(2,1)) to the space-to-depth input,
    computes exactly the original 7x7/stride-2/pad-3 conv: pad the taps
    to 8x8 (one leading zero row/col — stride-2 phase alignment), split
    each spatial axis into (block, phase), and fold the phases into the
    input-channel axis in the same (h2, w2, c) order as the data."""
    kh, kw, cin, cout = w_hwio.shape
    assert (kh, kw) == (7, 7), (kh, kw)
    wp = jnp.pad(w_hwio, ((1, 0), (1, 0), (0, 0), (0, 0)))
    wp = wp.reshape(4, 2, 4, 2, cin, cout)
    return wp.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * cin, cout)


def stem_s2d_conv_nchw(x, w_oihw, *, precision=None):
    """The full stem substitution on NCHW tensors: NHWC-ify, space-to-
    depth both operands, run the dense 4x4/stride-1 conv, NCHW-ify.
    Numerically the same taps in a different association (zero-padded
    positions contribute exact zeros)."""
    xt = space_to_depth_nhwc(jnp.transpose(x, (0, 2, 3, 1)))
    ws = space_to_depth_stem_kernel(jnp.transpose(w_oihw, (2, 3, 1, 0)))
    y = lax.conv_general_dilated(
        xt, ws.astype(xt.dtype), window_strides=(1, 1),
        padding=((2, 1), (2, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=decode_precision(precision))
    return jnp.transpose(y, (0, 3, 1, 2))


def _is_stem_shape(w_oihw, strides, padding, dilation, groups,
                   hw) -> bool:
    return (w_oihw.shape[1] == 3 and w_oihw.shape[2:] == (7, 7)
            and tuple(strides) == (2, 2)
            and tuple(padding) == ((3, 3), (3, 3))
            and tuple(dilation) == (1, 1) and groups == 1
            and hw[0] % 2 == 0 and hw[1] % 2 == 0)


def conv_bn_act_nchw(x, w, gamma, beta, mean, var, *, eps,
                     strides=(1, 1), padding=((0, 0), (0, 0)),
                     dilation=(1, 1), groups=1, relu=True,
                     impl="auto", precision=None):
    """Inference-mode ``relu?(batch_norm(conv(x, w)))`` with the BN
    folded into the conv — NCHW in, NCHW out (the rewrite anchor's
    aval), NHWC inside. ``w`` is OIHW; BN stats/affine are per-channel
    [C]. Stem-shaped convs additionally take the space-to-depth form."""
    s = (gamma.astype(jnp.float32)
         * lax.rsqrt(var.astype(jnp.float32) + eps))
    bias = beta.astype(jnp.float32) - mean.astype(jnp.float32) * s
    wf = w.astype(jnp.float32) * s[:, None, None, None]
    if _is_stem_shape(w, strides, padding, dilation, groups,
                      x.shape[2:]):
        xt = space_to_depth_nhwc(jnp.transpose(x, (0, 2, 3, 1)))
        wt = space_to_depth_stem_kernel(jnp.transpose(wf, (2, 3, 1, 0)))
        out = conv_bias_act(xt, wt, bias, strides=(1, 1),
                            padding=((2, 1), (2, 1)), relu=relu,
                            impl=impl, precision=precision)
    else:
        out = conv_bias_act(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(wf, (2, 3, 1, 0)), bias,
            strides=strides, padding=padding, dilation=dilation,
            groups=groups, relu=relu, impl=impl, precision=precision)
    return jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype)

"""Fused ops: TPU-first counterparts of the reference's fused kernel zoo
(paddle/phi/kernels/fusion/). Each op here is either a Pallas kernel or a
custom-vjp composition shaped so XLA keeps it fused and sharded."""
from .cross_entropy import (
    fused_softmax_cross_entropy,
    vocab_parallel_cross_entropy,
)

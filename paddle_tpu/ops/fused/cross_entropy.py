"""Fused softmax cross-entropy that never materialises log-probabilities.

Counterpart of the reference's ``_c_softmax_with_cross_entropy``
(python/paddle/distributed/fleet/layers/mpu/mp_ops.py:414 and the CUDA
kernel paddle/phi/kernels/gpu/c_softmax_with_cross_entropy_kernel.cu):
that op exists so a vocab-sharded (tensor-parallel) LM head never has to
all-gather its ``[B, T, V]`` logits — each rank reduces max / sum-exp /
label-logit locally and allreduces three small ``[B, T]`` tensors.

TPU-native version: one fused op with a custom VJP.

* Forward keeps all ``[B, T, V]``-sized math in the logits dtype
  (bf16 in the flagship path) and reduces to f32 ``[B, T]`` statistics
  on the fly — no f32 ``[B, T, V]`` log-softmax is ever written to HBM
  (the naive formulation materialises one and saves it for backward).
* The label logit is picked with a one-hot mask + reduction rather than
  a gather, so under GSPMD a vocab-sharded logits array needs only
  elementwise work per shard plus tiny cross-shard reductions: XLA emits
  exactly the max-allreduce / sum-allreduce pattern the reference
  hand-codes, and never an all-gather of the logits
  (tests/test_fused_ce.py asserts this on the compiled HLO).
* Backward is the closed form ``softmax(logits) - onehot(labels)`` scaled
  by the cotangent, recomputed from the saved bf16 logits + f32 lse —
  the only residuals are tensors the surrounding graph already has.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _stats(logits, labels):
    """f32 (lse, label_logit) of shape labels.shape, GSPMD-friendly."""
    lf = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(lf, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1)) + m[..., 0]
    onehot = (jnp.arange(logits.shape[-1], dtype=jnp.int32)
              == labels[..., None])
    label_logit = jnp.sum(jnp.where(onehot, lf, 0.0), axis=-1)
    return lse, label_logit


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_softmax_cross_entropy(logits, labels, ignore_index: int = -100):
    """Per-token NLL: ``logsumexp(logits) - logits[labels]``, f32.

    logits: ``[..., V]`` any float dtype (kept in that dtype for the bulk
    math); labels: ``[...]`` int. Positions where ``labels == ignore_index``
    get loss 0 and zero gradient.
    """
    lse, label_logit = _stats(logits, jnp.maximum(labels, 0))
    nll = lse - label_logit
    return jnp.where(labels == ignore_index, 0.0, nll)


def _fused_ce_fwd(logits, labels, ignore_index):
    safe = jnp.maximum(labels, 0)
    lse, label_logit = _stats(logits, safe)
    nll = lse - label_logit
    out = jnp.where(labels == ignore_index, 0.0, nll)
    return out, (logits, labels, lse)


def _fused_ce_bwd(ignore_index, res, g):
    logits, labels, lse = res
    valid = labels != ignore_index
    g = jnp.where(valid, g, 0.0).astype(jnp.float32)
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (jnp.arange(logits.shape[-1], dtype=jnp.int32)
              == jnp.maximum(labels, 0)[..., None])
    grad = (p - jnp.where(onehot, 1.0, 0.0)) * g[..., None]
    return grad.astype(logits.dtype), None


fused_softmax_cross_entropy.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def vocab_parallel_cross_entropy(logits, labels, axis_name: str,
                                 vocab_start: int | None = None):
    """Explicit-collective variant for use *inside* ``shard_map``.

    ``logits`` is this shard's ``[..., V/tp]`` slice; ``labels`` are global
    ids. Reduces max / sum-exp / label-logit with ``psum``/``pmax`` over
    ``axis_name`` — the literal TPU translation of the reference kernel
    (mp_ops.py:414), three ``[B, T]`` collectives and no logits gather.

    Differentiable INSIDE the shard_map body via a custom VJP: backward
    is the closed-form ``softmax_local - onehot_local`` — purely local
    math off the saved (globally reduced) lse, so an in-body
    ``jax.vjp`` (the async pipeline head, parallel/pipeline_async.py)
    never transposes a raw ``psum`` (which jax would turn into another
    psum, over-counting by the axis size — see parallel/mp_ops.py).

    ``vocab_start`` defaults to ``axis_index * local_V``.
    """
    local_v = logits.shape[-1]
    if vocab_start is None:
        vocab_start = jax.lax.axis_index(axis_name) * local_v
    return _vp_ce(logits, labels,
                  jnp.asarray(vocab_start, jnp.int32), axis_name)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _vp_ce(logits, labels, vocab_start, axis_name: str):
    return _vp_ce_fwd(logits, labels, vocab_start, axis_name)[0]


def _vp_ce_fwd(logits, labels, vocab_start, axis_name):
    lf = logits.astype(jnp.float32)
    m = jax.lax.pmax(jnp.max(lf, axis=-1), axis_name)
    s = jax.lax.psum(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1),
                     axis_name)
    lse = jnp.log(s) + m
    local_ids = labels[..., None] - vocab_start
    onehot = (jnp.arange(logits.shape[-1], dtype=jnp.int32) == local_ids)
    label_logit = jax.lax.psum(
        jnp.sum(jnp.where(onehot, lf, 0.0), axis=-1), axis_name)
    return lse - label_logit, (logits, labels, vocab_start, lse)


def _vp_ce_bwd(axis_name, res, g):
    logits, labels, vocab_start, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    local_ids = labels[..., None] - vocab_start
    onehot = (jnp.arange(logits.shape[-1], dtype=jnp.int32) == local_ids)
    grad = (p - jnp.where(onehot, 1.0, 0.0)) * g[..., None].astype(
        jnp.float32)
    return grad.astype(logits.dtype), None, None


_vp_ce.defvjp(_vp_ce_fwd, _vp_ce_bwd)

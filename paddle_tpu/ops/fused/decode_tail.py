"""Fused serving decode tail: last-row gather + final RMSNorm + lm_head.

Reference capability: the fused lm-head epilogues of the deployed
inference graphs (paddle/phi/kernels/fusion/ — e.g.
fused_bias_act/fused_linear chains the IR passes stitch onto the last
decode op). In the serving tick the tail is

    ``logits = (rms_norm(h)[last] @ lm_head).astype(f32)``

— per-op it measures under 1% of step time, but it costs separate
launches and an HBM round-trip of the FULL ``[T, D]`` normed stream per
tick when only ``S`` rows are read. The decode-tail rewrite pass
(analysis/rewrite.py) substitutes this entry point, which:

* gathers the ``S`` live rows FIRST (rms_norm is row-local, so
  norm∘gather == gather∘norm exactly — the dead ``T−S`` rows are never
  normalised, and the pre-head HBM traffic drops from ``T·D`` to
  ``S·D``);
* routes the norm through the Pallas ``fused_rms_norm`` kernel (the
  kernel-substitution contract the fused-rmsnorm pass already pins,
  and an opaque call the rewriter cannot re-match — idempotence);
* leaves the head matmul adjacent so XLA (or a later authored kernel)
  consumes the normed rows straight out of registers/VMEM.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fused_decode_tail"]


def fused_decode_tail(x, w, idx, head, *, eps, out_dtype=jnp.float32):
    """``(rms_norm(x, w, eps)[idx] @ head).astype(out_dtype)`` with the
    gather hoisted above the norm. ``x`` [T, D] packed hidden stream,
    ``w`` [D] norm weight, ``idx`` int [S] row indices (negative wraps,
    same as jnp indexing), ``head`` [D, V].

    The head matmul runs in ``head.dtype`` — in the AMP serving graphs
    the normed f32 rows are cast DOWN to bf16 before the dot, and the
    substitution must mirror that (computing the dot in f32 instead is
    *more* precise, but reads as drift against the original under the
    exactness contract)."""
    from ..pallas.fused_norm_rope import fused_rms_norm
    rows = x[idx]
    rows = fused_rms_norm(rows, w, float(eps))
    return (rows.astype(head.dtype) @ head).astype(out_dtype)

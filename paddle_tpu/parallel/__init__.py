"""paddle_tpu.parallel — device-mesh topology and SPMD parallelism.

TPU-native replacement for the reference's fleet hybrid-parallel stack
(python/paddle/distributed/fleet/base/topology.py:70,189-238 and
meta_parallel/): instead of NCCL process groups per axis, one
``jax.sharding.Mesh`` with named axes carries every parallelism dimension,
and XLA GSPMD inserts the collectives over ICI.
"""
from .mesh import (
    HybridMesh,
    init_hybrid_mesh,
    get_hybrid_mesh,
    mesh_axis_size,
    P,
)
from .pipeline_spmd import pipeline_spmd, stack_stage_params
from .pipeline_1f1b import (
    pipeline_train_1f1b,
    schedule_efficiency,
    schedule_ticks,
    split_chunks_round_robin,
)
from .pipeline_async import (
    Schedule,
    build_schedule,
    pipeline_train_async,
)
from .mp_ops import (
    identity_fwd_psum_bwd,
    psum_fwd_identity_bwd,
)
from .context_parallel import (
    ring_attention,
    ulysses_attention,
    context_parallel_attention,
)

"""Rank-asymmetric 1F1B / zero-bubble pipeline schedules.

The lockstep traced schedule (``pipeline_1f1b.py``) runs every slot on
every tick — fill/drain manifests as masked work, a (2S-1)/(M+2S-1)
tick fraction that lags the reference's per-rank 1F1B by 10-20
efficiency points at pp>=4 (tools/pipeline_ceiling.py, docs/PERF.md).
The reference kills that bubble with PER-RANK schedules
(pipeline_parallel.py:565 forward_backward_pipeline,
pipeline_zero_bubble.py): each rank runs warmup forwards, a steady
1F1B interleave, and a drain tail — DIFFERENT code per rank. This
module expresses that under XLA as one SPMD program:

  * a HOST-side schedule builder computes, for every ``(tick, rank)``,
    which op runs — forward (F), input-grad backward (B), deferred
    weight-grad (W), forward+loss-head (FH on the last rank), or idle —
    via a greedy list scheduler over the true data dependencies
    (1-tick neighbour latency), then register-allocates every saved
    activation/cotangent into a bounded ring (the O(S)-not-O(M)
    1F1B memory property, now proven per schedule by interval
    allocation instead of asserted);
  * a TRACED executor (`pipeline_train_async`) wraps one
    ``lax.scan`` over ticks in a ``shard_map`` over the ``pp`` axis.
    The scan body branches on the prefetched op code with
    ``lax.switch`` — ``lax.axis_index("pp")`` picks each rank's column
    of the op table, so every device executes ONLY its own rank's op
    for the tick (a real branch at runtime, not masked lockstep work).
    Neighbour exchange is one up- and one down-``ppermute`` per tick,
    unconditional, so the collective signature is identical on every
    rank by construction.

Variants (``schedule_ticks`` / ``schedule_efficiency`` model both):

  * ``"1f1b"`` — classic rank-asymmetric 1F1B: ticks are half-steps
    (one F or one full backward per rank). Span = 2(VM + S - 1) ticks,
    efficiency VM/(VM + S - 1) — the reference 1F1B bubble exactly
    (0.889 at pp=2/M=8, 0.970 at M=32), including interleaved V>1
    (efficiency 1 - (S-1)/(VM + S - 1), the VPP fill-shrink the
    lockstep form could not express).
  * ``"zb"`` — ZB-H1-style W-deferral (pipeline_zero_bubble.py): the
    backward splits into B (input grads, critical path) and W (weight
    grads, deferred into bubble slots; backlog bounded by S so the
    saved-tensor ring stays O(S)). Span = 3VM + fill/drain remainder —
    strictly above the 1F1B bound at every geometry. Honest cost: B's
    ``jax.vjp`` re-runs the stage forward (a pullback cannot cross
    scan ticks), but its RESIDUALS — the pullback's own pytree leaves
    — are ring-saved (interval-colored like the sx/sc rings, depths
    still exactly M-independent), so W restores the saved pullback and
    computes weight grads with NO second forward replay: ~4.5 work
    units per microbatch-stage vs the fused backward's 4 (the dW pass
    still re-walks the cotangent chain — docs/PERF.md r19 quantifies
    the cut from the r14 5/4).

Numerics are IDENTICAL to the lockstep schedule by construction: the
same per-microbatch stage/head functions, f32 grad accumulation in the
same per-stage microbatch order, mean over M — every existing pipeline
exactness test doubles as a correctness pin for this module
(tests/test_pipeline_async.py asserts loss+grads match lockstep and
plain single-stage autodiff).

Mesh composition (r19, ROADMAP item 4's roll-forward): the shard_map
now spans the FULL ``(dp, tp, pp)`` mesh. The op-table scan and the
up/down ppermute pair run along ``pp`` exactly as before; ``dp``
shards the microbatch rows (the caller's ``x_spec``), with the dp
gradient psum folded into the f32 accumulation carry AFTER the scan —
one psum per accumulator leaf, not per microbatch — and loss/ghead
psum'd over dp×pp; ``tp`` shards the stage weights per the caller's
``stage_specs``, with the stage/head bodies doing their own in-body
collectives (models/llama.py `_tp_local_block`: megatron f/g custom
ops from parallel/mp_ops.py + vocab-parallel CE). Axes other than
dp/tp/pp (cp, ep) must still be size 1.
"""
from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# op codes — ALSO the lax.switch branch order in pipeline_train_async
IDLE, OP_F, OP_B, OP_FH, OP_W = 0, 1, 2, 3, 4
KIND_NAMES = {IDLE: "idle", OP_F: "F", OP_B: "B", OP_FH: "F+head",
              OP_W: "W"}
VARIANTS = ("1f1b", "zb")

#: the ONE statement of what each pp_schedule config value means:
#: LlamaConfig.pp_schedule -> (schedule-model name spoken by
#: schedule_ticks/schedule_efficiency, executor variant — None = the
#: lockstep pipeline_1f1b executor). llama, analysis/training_graphs
#: and tools/pipeline_ceiling all derive from this so a new schedule
#: cannot desynchronize them.
PP_SCHEDULES = {
    "1f1b": ("lockstep", None),
    "1f1b_async": ("1f1b", "1f1b"),
    "zb": ("zb", "zb"),
}


@dataclasses.dataclass(frozen=True)
class ScheduleInfo:
    """Queryable metadata for one ``pp_schedule`` value — the legality
    constraints and cost facts that used to live as raise-sites inside
    the builders and prose inside docstrings. The auto-parallel planner
    (analysis/planner.py) enumerates its search space from this table;
    ``schedule_legality`` below is derived from the same fields the
    executors enforce, so a constraint added to one cannot silently
    miss the other.

    ``work_units_per_mb_stage``: relative compute units one microbatch
    costs one stage (F=1, fused backward=3). The zb variant's B
    re-runs the stage forward inside its ``jax.vjp`` and W re-walks
    the cotangent chain from the ring-saved residuals (no second
    forward replay — r19's residual-ring cut from the r14 5/4) —
    ~4.5 units vs 4 (docs/PERF.md r19) — which the planner prices as
    a flop multiplier.
    ``lockstep_masked_work``: the schedule executes every slot every
    tick, so (1 - efficiency) is REAL extra compute, not idle time.
    """
    name: str                   # LlamaConfig.pp_schedule value
    model: str                  # schedule_ticks/schedule_efficiency name
    executor: Optional[str]     # pipeline_async variant; None = lockstep
    requires_dp1_tp1: bool      # True only for a schedule whose stage
    #                             body cannot compose dp/tp (none today)
    supports_vpp: bool          # virtual_chunks > 1 allowed
    vpp_needs_divisible_M: bool  # V>1 requires M % S == 0
    min_stages: int
    work_units_per_mb_stage: float
    lockstep_masked_work: bool

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


#: pp_schedule name -> ScheduleInfo. Consistent with PP_SCHEDULES by
#: construction (asserted at import below).
SCHEDULE_INFO: Dict[str, ScheduleInfo] = {
    "1f1b": ScheduleInfo(
        name="1f1b", model="lockstep", executor=None,
        requires_dp1_tp1=False, supports_vpp=True,
        vpp_needs_divisible_M=False, min_stages=1,
        work_units_per_mb_stage=4, lockstep_masked_work=True),
    "1f1b_async": ScheduleInfo(
        name="1f1b_async", model="1f1b", executor="1f1b",
        requires_dp1_tp1=False, supports_vpp=True,
        vpp_needs_divisible_M=True, min_stages=2,
        work_units_per_mb_stage=4, lockstep_masked_work=False),
    "zb": ScheduleInfo(
        name="zb", model="zb", executor="zb",
        requires_dp1_tp1=False, supports_vpp=False,
        vpp_needs_divisible_M=True, min_stages=2,
        work_units_per_mb_stage=4.5, lockstep_masked_work=False),
}
assert set(SCHEDULE_INFO) == set(PP_SCHEDULES) and all(
    (i.model, i.executor) == PP_SCHEDULES[n]
    for n, i in SCHEDULE_INFO.items())

#: executor variant -> pp_schedule name (build_schedule speaks variant)
_VARIANT_TO_SCHEDULE = {v: n for n, (_, v) in PP_SCHEDULES.items()
                        if v is not None}


def schedule_legality(name: str, *, num_stages: int,
                      num_microbatches: int, virtual_chunks: int = 1,
                      dp: int = 1, tp: int = 1) -> Optional[str]:
    """None when ``(schedule, geometry)`` is legal, else the reason it
    is not — the ONE statement of schedule legality. ``build_schedule``
    raises exactly these reasons for its subset (asserted by the
    rejection tests), ``pipeline_train_async`` enforces the mesh-axis
    restriction at run time, and the planner prunes its search space
    with the same answers, so legality cannot drift between the three.

    ``dp``/``tp`` are accepted for any schedule since r19 (the
    executor composes both into the shard_map — model-level
    divisibility like heads-per-tp-shard is the planner's/caller's
    mesh-level check, not a schedule property); the parameters remain
    so a future schedule that genuinely cannot compose can gate on
    them via ``requires_dp1_tp1``.
    """
    info = SCHEDULE_INFO.get(name)
    if info is None:
        return (f"variant must be one of {tuple(SCHEDULE_INFO)}, "
                f"got {name!r}")
    S, M, V = int(num_stages), int(num_microbatches), int(virtual_chunks)
    if M < 1 or V < 1:
        return "need num_microbatches >= 1, virtual_chunks >= 1"
    if S < info.min_stages:
        if info.min_stages >= 2:
            return ("rank-asymmetric schedules need num_stages >= 2 "
                    "(pp=1 has no pipeline bubble — use the plain or "
                    "lockstep path)")
        return f"need num_stages >= {info.min_stages}"
    if V > 1 and not info.supports_vpp:
        return ("zb W-deferral with virtual_chunks > 1 (ZB-V-style "
                "schedules) is not supported — the reference's "
                "pipeline_zero_bubble.py ZB-H1 is V=1 too; use "
                "variant='1f1b' for interleaved VPP")
    if V > 1 and info.vpp_needs_divisible_M and M % S:
        return (f"interleaved V>1 needs num_microbatches divisible by "
                f"num_stages (the reference's VPP constraint), got "
                f"M={M} S={S}")
    if info.requires_dp1_tp1 and (int(dp) > 1 or int(tp) > 1):
        return (f"schedule {name!r} currently requires every non-pp "
                f"mesh axis to be size 1 (the shard_map stage body is "
                f"a single-device program); got dp={dp} tp={tp}")
    return None


@dataclasses.dataclass(frozen=True)
class Schedule:
    """One built rank-asymmetric schedule: the static op/routing tables
    the traced executor consumes, plus the bookkeeping tests pin.

    All tables are int32 ``[ticks, S]`` (tick-major so ``lax.scan`` can
    slice per-tick rows): ``kind`` (op codes above), ``chunk``/``mb``
    (which (virtual chunk, microbatch) the op touches), ``slot_x`` /
    ``slot_c`` (saved-activation / saved-cotangent ring slots the op
    reads — for F with ``inject`` set, the slot it WRITES the injected
    input to), ``slot_r`` (zb only: the residual-ring slot B WRITES its
    pullback's residual leaves to and W READS them from — what lets W
    skip the stage-forward replay), ``inject`` (F consumes ``x[mb]``
    instead of an arrival), ``emit`` (B's dx is the stage-0 embedding
    cotangent), ``store_up`` / ``store_dn`` (ring slot where this rank
    stores the value arriving on the up/down ppermute at the END of
    the tick; -1 = none/discard).
    """
    num_stages: int
    num_microbatches: int
    virtual_chunks: int
    variant: str
    ticks: int
    depth_x: int          # saved-activation ring depth (max over ranks)
    depth_c: int          # saved-cotangent ring depth
    depth_r: int          # saved-residual ring depth (zb; 0 otherwise)
    kind: np.ndarray
    chunk: np.ndarray
    mb: np.ndarray
    slot_x: np.ndarray
    slot_c: np.ndarray
    slot_r: np.ndarray
    inject: np.ndarray
    emit: np.ndarray
    store_up: np.ndarray
    store_dn: np.ndarray

    @property
    def useful_ticks_per_rank(self) -> int:
        per_mb = 3 if self.variant == "zb" else 2
        return per_mb * self.virtual_chunks * self.num_microbatches

    @property
    def efficiency(self) -> float:
        """Non-idle fraction of each rank's ticks — the schedule-bubble
        measure the reference's 1F1B/ZB numbers are quoted in."""
        return self.useful_ticks_per_rank / self.ticks

    def op_counts(self) -> Dict[str, int]:
        """rank-tick counts per op kind over the whole schedule."""
        out = {}
        for code, name in KIND_NAMES.items():
            out[name] = int((self.kind == code).sum())
        return out


def _f_dest(S: int, V: int, v: int, s: int, m: int
            ) -> Optional[Tuple[int, int, int]]:
    """Where chunk (v, s)'s F output lands: (v, s+1) one rank up, or
    the ring wrap (v+1, 0) from the last rank to rank 0. None for the
    last chunk's F (= FH — the loss head consumes it locally).

    The ONE statement of the forward routing: both schedule builders
    AND the store_up table construction use it (``_validate``
    re-states it independently, on purpose — it is the check)."""
    if v == V - 1 and s == S - 1:
        return None
    if s == S - 1:
        return (v + 1, 0, m)
    return (v, s + 1, m)


def _b_dest(S: int, V: int, v: int, s: int, m: int
            ) -> Optional[Tuple[int, int, int]]:
    """Where B's dx cotangent lands: (v, s-1) one rank down, or the
    wrap (v-1, S-1) from rank 0 back to the last rank. None at chunk
    (0, 0) — that dx is the embedding cotangent (emitted)."""
    if v == 0 and s == 0:
        return None
    if s == 0:
        return (v - 1, S - 1, m)
    return (v, s - 1, m)


def _interleaved_order(S: int, s: int, M: int, V: int
                       ) -> List[Tuple[str, int, int]]:
    """Rank ``s``'s fixed op order for interleaved V>1 — the
    reference's VPP pattern (pipeline_parallel.py:1372, same shape as
    Megatron's interleaved 1F1B): microbatches run in groups of S;
    forwards cycle chunks 0..V-1 per group, backwards cycle V-1..0;
    warmup = 2(S-s-1) + (V-1)S + 1 forwards (the Megatron count, +1
    because the steady-state pair here is F-then-B against our 1-tick
    arrival latency), then strict F,B pairs, then the backward drain.
    Greedy choice cannot reproduce this pattern (it deadlocks against
    the wrap dependencies), so V>1 uses the fixed order and — like
    the reference — requires M % S == 0."""
    total = V * M

    def f_op(k):
        return (k // S) % V, (k // (S * V)) * S + k % S

    def b_op(k):
        return V - 1 - ((k // S) % V), (k // (S * V)) * S + k % S

    warmup = min(2 * (S - s - 1) + (V - 1) * S + 1, total)
    ops: List[Tuple[str, int, int]] = [
        ("F",) + f_op(k) for k in range(warmup)]
    for k in range(total - warmup):
        ops.append(("F",) + f_op(warmup + k))
        ops.append(("B",) + b_op(k))
    for k in range(total - warmup, total):
        ops.append(("B",) + b_op(k))
    return ops


def _fixed_order_schedule(S: int, M: int, V: int
                          ) -> List[List[Tuple[int, int, int]]]:
    """Earliest-feasible tick assignment of the fixed interleaved op
    order: each rank executes its list strictly in order, idling while
    the next op's input has not arrived (1-tick neighbour latency)."""
    orders = {s: _interleaved_order(S, s, M, V) for s in range(S)}
    ptr = {s: 0 for s in range(S)}
    act_arr: Dict[Tuple[int, int, int], int] = {}
    ct_arr: Dict[Tuple[int, int, int], int] = {}
    grid: List[List[Tuple[int, int, int]]] = []
    limit = 8 * (2 * V * M + 2 * S * V) + 64
    t = 0
    while any(ptr[s] < len(orders[s]) for s in range(S)):
        if t >= limit:
            raise AssertionError(
                f"fixed-order schedule stalled for S={S} M={M} V={V}")
        row: List[Tuple[int, int, int]] = []
        for s in range(S):
            op = (IDLE, 0, 0)
            if ptr[s] < len(orders[s]):
                what, v, m = orders[s][ptr[s]]
                if what == "F":
                    ready = (v == 0 and s == 0) or \
                        act_arr.get((v, s, m), t) <= t - 1
                    if ready:
                        kind = (OP_FH if (v == V - 1 and s == S - 1)
                                else OP_F)
                        op = (kind, v, m)
                else:
                    if ct_arr.get((v, s, m), t) <= t - 1:
                        op = (OP_B, v, m)
            row.append(op)
        for s, (kind, v, m) in enumerate(row):
            if kind == IDLE:
                continue
            ptr[s] += 1
            if kind == OP_FH:
                ct_arr[(v, s, m)] = t          # head ct, local
            elif kind == OP_F:
                act_arr[_f_dest(S, V, v, s, m)] = t
            elif kind == OP_B:
                dst = _b_dest(S, V, v, s, m)
                if dst is not None:            # (0,0): dx -> embedding
                    ct_arr[dst] = t
        grid.append(row)
        t += 1
    return grid


def _greedy_schedule(S: int, M: int, variant: str
                     ) -> List[List[Tuple[int, int, int]]]:
    """Greedy list scheduler for V=1 -> grid[t][s] = (kind, 0, m)
    (interleaved V>1 goes through ``_fixed_order_schedule`` instead —
    greedy choice deadlocks against the ring-wrap dependencies there).

    Per tick, per rank, priority order:
      1. B, microbatch FIFO (the critical path);
      2. forced W when the deferred-W backlog hits S (bounds the
         saved-tensor ring at O(S) — the ZB-H1 memory discipline);
      3. F in microbatch order (injected at rank 0, arrival-gated
         elsewhere), capped at S - s in-flight microbatches per rank
         (the classic 1F1B warmup depth — what bounds activation
         memory independent of M);
      4. any W (bubble filler — the entire point of ZB);
      5. idle.
    """
    zb = variant == "zb"
    fdone: Dict[Tuple[int, int, int], int] = {}
    bdone: Dict[Tuple[int, int, int], int] = {}
    wdone: Dict[Tuple[int, int, int], int] = {}
    act_arr: Dict[Tuple[int, int, int], int] = {}
    ct_arr: Dict[Tuple[int, int, int], int] = {}
    total = S * M * (3 if zb else 2)
    done = 0
    grid: List[List[Tuple[int, int, int]]] = []
    limit = 6 * (3 * M + 2 * S) + 64
    t = 0

    def w_backlog(s, t):
        return sorted(
            (bdone[k], k) for k in bdone
            if k[1] == s and k not in wdone and bdone[k] <= t - 1)

    while done < total:
        if t >= limit:
            raise AssertionError(
                f"schedule builder did not converge for S={S} M={M} "
                f"variant={variant!r} after {limit} ticks")
        row: List[Tuple[int, int, int]] = []
        for s in range(S):
            op = (IDLE, 0, 0)
            # -- 1. B -------------------------------------------------
            cand_b = [
                m for m in range(M)
                if (0, s, m) in fdone and (0, s, m) not in bdone
                and ct_arr.get((0, s, m), t) <= t - 1]
            if cand_b:
                op = (OP_B, 0, min(cand_b))
            elif zb and len(w_backlog(s, t)) >= S:
                _, (v, _s, m) = w_backlog(s, t)[0]
                op = (OP_W, v, m)
            if op[0] == IDLE:
                # -- 3. F ---------------------------------------------
                inflight = sum(
                    1 for m in range(M)
                    if (0, s, m) in fdone and (0, s, m) not in bdone)
                if inflight < S - s:
                    m = next((m for m in range(M)
                              if (0, s, m) not in fdone), None)
                    if m is not None and (
                            s == 0
                            or act_arr.get((0, s, m), t) <= t - 1):
                        op = (OP_FH if s == S - 1 else OP_F, 0, m)
            if op[0] == IDLE and zb and w_backlog(s, t):
                # -- 4. W filler --------------------------------------
                _, (v, _s, m) = w_backlog(s, t)[0]
                op = (OP_W, v, m)
            row.append(op)
        # apply the whole tick's decisions, then record arrivals (end
        # of tick t -> usable from t + 1)
        for s, (kind, v, m) in enumerate(row):
            if kind in (OP_F, OP_FH):
                fdone[(v, s, m)] = t
                if kind == OP_FH:
                    ct_arr[(v, s, m)] = t      # head ct, local
                else:
                    act_arr[_f_dest(S, 1, v, s, m)] = t
                done += 1
            elif kind == OP_B:
                bdone[(v, s, m)] = t
                dst = _b_dest(S, 1, v, s, m)
                if dst is not None:            # (0,0): dx -> embedding
                    ct_arr[dst] = t
                done += 1
            elif kind == OP_W:
                wdone[(v, s, m)] = t
                done += 1
        grid.append(row)
        t += 1
    return grid


def _validate(grid, S: int, M: int, V: int, variant: str) -> None:
    """Replay the grid asserting every dependency with 1-tick latency.
    Independent of the greedy builder: a scheduling bug fails HERE, at
    build time, not as silently-wrong gradients."""
    zb = variant == "zb"
    fdone, bdone, wdone, act_arr, ct_arr = {}, {}, {}, {}, {}
    for t, row in enumerate(grid):
        assert len(row) == S
        for s, (kind, v, m) in enumerate(row):
            key = (v, s, m)
            if kind in (OP_F, OP_FH):
                assert key not in fdone, f"double F {key}"
                if v == 0 and s == 0:
                    for mp in range(m):   # injects strictly in order
                        assert (0, 0, mp) in fdone, (t, key)
                else:
                    assert act_arr.get(key, t) <= t - 1, \
                        f"F{key} @t{t}: input not arrived"
                assert (kind == OP_FH) == (v == V - 1 and s == S - 1)
            elif kind == OP_B:
                assert key in fdone and fdone[key] < t, (t, key)
                assert ct_arr.get(key, t) <= t - 1, \
                    f"B{key} @t{t}: cotangent not arrived"
                assert key not in bdone
            elif kind == OP_W:
                assert zb and key in bdone and bdone[key] < t, (t, key)
                assert key not in wdone
            else:
                assert kind == IDLE
            # arrivals (same bookkeeping as the builder)
            if kind in (OP_F, OP_FH):
                fdone[key] = t
                if kind == OP_FH:
                    ct_arr[key] = t
                elif s == S - 1:
                    act_arr[(v + 1, 0, m)] = t
                else:
                    act_arr[(v, s + 1, m)] = t
            elif kind == OP_B:
                bdone[key] = t
                if s == 0 and v > 0:
                    ct_arr[(v - 1, S - 1, m)] = t
                elif s > 0:
                    ct_arr[(v, s - 1, m)] = t
            elif kind == OP_W:
                wdone[key] = t
    want = {(v, s, m) for v in range(V) for s in range(S)
            for m in range(M)}
    assert set(fdone) == want, "missing forwards"
    assert set(bdone) == want, "missing backwards"
    if zb:
        assert set(wdone) == want, "missing deferred weight grads"


def _alloc_slots(intervals: List[Tuple[int, int, Any]]
                 ) -> Tuple[Dict[Any, int], int]:
    """Greedy interval-graph coloring: values -> ring slots. A slot
    whose value was last READ at tick e is reusable by a value STORED
    at the end of tick e or later (stores happen end-of-tick, reads
    during the following ticks). Returns (value -> slot, depth)."""
    slots_free_at: List[int] = []
    assign: Dict[Any, int] = {}
    for store, last_read, key in sorted(intervals):
        for i, free_at in enumerate(slots_free_at):
            if free_at <= store:
                assign[key] = i
                slots_free_at[i] = last_read
                break
        else:
            assign[key] = len(slots_free_at)
            slots_free_at.append(last_read)
    return assign, len(slots_free_at)


@lru_cache(maxsize=None)
def build_schedule(num_stages: int, num_microbatches: int,
                   virtual_chunks: int = 1,
                   variant: str = "1f1b") -> Schedule:
    """Build + validate + register-allocate one schedule (cached)."""
    S, M, V = int(num_stages), int(num_microbatches), int(virtual_chunks)
    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}, "
                         f"got {variant!r}")
    # legality lives in ONE queryable table (schedule_legality /
    # SCHEDULE_INFO) shared with the planner's search-space pruning;
    # the builder raises exactly its reasons
    reason = schedule_legality(
        _VARIANT_TO_SCHEDULE[variant], num_stages=S,
        num_microbatches=M, virtual_chunks=V)
    if reason is not None:
        raise ValueError(reason)
    zb = variant == "zb"
    if V > 1:
        grid = _fixed_order_schedule(S, M, V)
    else:
        grid = _greedy_schedule(S, M, variant)
    _validate(grid, S, M, V, variant)
    T = len(grid)

    # -- op-time lookup ----------------------------------------------
    ftick, btick, wtick = {}, {}, {}
    for t, row in enumerate(grid):
        for s, (kind, v, m) in enumerate(row):
            if kind in (OP_F, OP_FH):
                ftick[(v, s, m)] = t
            elif kind == OP_B:
                btick[(v, s, m)] = t
            elif kind == OP_W:
                wtick[(v, s, m)] = t

    # -- saved-value intervals per rank ------------------------------
    # ACT(v,s,m): stage input. Stored at arrival (end of the sender's F
    # tick) or, for stage-0 chunk-0 injects, during its own F tick;
    # read by F (non-inject) and B (W consumes the residual ring, not
    # the input — it never replays the stage forward).
    # CT(v,s,m): incoming cotangent. Stored at arrival / the FH tick;
    # read by B and (zb) W.
    # RES(v,s,m) (zb): B's pullback residual leaves. Stored during the
    # B tick, read once by W — the interval that prices the W-replay
    # cut's memory.
    x_assign: Dict[int, Dict[Tuple[int, int], int]] = {}
    c_assign: Dict[int, Dict[Tuple[int, int], int]] = {}
    r_assign: Dict[int, Dict[Tuple[int, int], int]] = {}
    depth_x = depth_c = 1
    depth_r = 0
    for s in range(S):
        xiv, civ, riv = [], [], []
        for v in range(V):
            for m in range(M):
                f_t = ftick[(v, s, m)]
                last = wtick[(v, s, m)] if zb else btick[(v, s, m)]
                if v == 0 and s == 0:
                    store = f_t
                else:
                    if s == 0:
                        store = ftick[(v - 1, S - 1, m)]
                    else:
                        store = ftick[(v, s - 1, m)]
                xiv.append((store, btick[(v, s, m)], (v, m)))
                if v == V - 1 and s == S - 1:
                    c_store = f_t  # head ct, written during FH
                else:
                    if s == S - 1:
                        c_store = btick[(v + 1, 0, m)]
                    else:
                        c_store = btick[(v, s + 1, m)]
                civ.append((c_store, last, (v, m)))
                if zb:
                    riv.append((btick[(v, s, m)], wtick[(v, s, m)],
                                (v, m)))
        xa, dx = _alloc_slots(xiv)
        ca, dc = _alloc_slots(civ)
        x_assign[s], c_assign[s] = xa, ca
        depth_x, depth_c = max(depth_x, dx), max(depth_c, dc)
        if zb:
            ra, dr = _alloc_slots(riv)
            r_assign[s] = ra
            depth_r = max(depth_r, dr)

    # -- tables ------------------------------------------------------
    kind = np.zeros((T, S), np.int32)
    chunk = np.zeros((T, S), np.int32)
    mb = np.zeros((T, S), np.int32)
    slot_x = np.zeros((T, S), np.int32)
    slot_c = np.zeros((T, S), np.int32)
    slot_r = np.zeros((T, S), np.int32)
    inject = np.zeros((T, S), np.int32)
    emit = np.zeros((T, S), np.int32)
    store_up = np.full((T, S), -1, np.int32)
    store_dn = np.full((T, S), -1, np.int32)
    for t, row in enumerate(grid):
        for s, (k, v, m) in enumerate(row):
            kind[t, s], chunk[t, s], mb[t, s] = k, v, m
            if k == IDLE:
                continue
            slot_x[t, s] = x_assign[s][(v, m)]
            if k in (OP_B, OP_W) or (k == OP_FH):
                slot_c[t, s] = c_assign[s][(v, m)]
            if zb and k in (OP_B, OP_W):
                slot_r[t, s] = r_assign[s][(v, m)]
            if k in (OP_F, OP_FH) and v == 0 and s == 0:
                inject[t, s] = 1
            if k == OP_B and v == 0 and s == 0:
                emit[t, s] = 1
        # arrival routing (the same _f_dest/_b_dest the builders
        # scheduled with): rank r receives the up value from rank
        # (r-1)%S and the down value from rank (r+1)%S, end of tick t
        for r in range(S):
            k, v, m = row[(r - 1) % S]
            if k == OP_F:  # FH is consumed locally by the head
                tgt = _f_dest(S, V, v, (r - 1) % S, m)
                assert tgt is not None and tgt[1] == r
                store_up[t, r] = x_assign[r][(tgt[0], tgt[2])]
            k, v, m = row[(r + 1) % S]
            if k == OP_B:
                tgt = _b_dest(S, V, v, (r + 1) % S, m)
                if tgt is not None:  # None: (0,0) dx -> embedding
                    assert tgt[1] == r
                    store_dn[t, r] = c_assign[r][(tgt[0], tgt[2])]
    return Schedule(
        num_stages=S, num_microbatches=M, virtual_chunks=V,
        variant=variant, ticks=T, depth_x=depth_x, depth_c=depth_c,
        depth_r=depth_r, kind=kind, chunk=chunk, mb=mb, slot_x=slot_x,
        slot_c=slot_c, slot_r=slot_r, inject=inject, emit=emit,
        store_up=store_up, store_dn=store_dn)


# ---------------------------------------------------------------------------
# traced executor
# ---------------------------------------------------------------------------

def _spec_names(spec) -> set:
    """Flat set of mesh-axis names a PartitionSpec mentions."""
    out = set()
    for entry in tuple(spec) if spec is not None else ():
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, (tuple, list))
                   else (entry,)):
            out.add(ax)
    return out


def pipeline_train_async(
    stage_fn: Callable[[Any, Any], Any],
    head_fn: Callable[[Any, Any, Any], Any],
    stage_params: Any,
    head_params: Any,
    x: Any,
    aux: Any,
    *,
    num_stages: int,
    virtual_chunks: int = 1,
    variant: str = "1f1b",
    mesh: Any,
    stage_specs: Any = None,
    head_specs: Any = None,
    x_spec: Any = None,
    aux_specs: Any = None,
    _schedule: Optional[Schedule] = None,
    _drop_dp_grad_psum: bool = False,
):
    """One fused forward+backward pass under a rank-asymmetric schedule.

    Same contract as ``pipeline_1f1b.pipeline_train_1f1b`` (and the
    same return tuple ``(loss, grads_stage, grads_head, dx)``), but the
    schedule is per-rank: the scan body ``lax.switch``-es on the op
    table column selected by ``lax.axis_index("pp")`` inside a
    ``shard_map``, so warmup/steady/drain differ per rank and idle
    ticks execute a trivial branch instead of a masked full fwd+bwd.

    ``stage_params`` leaves are ``[V*S, ...]`` chunk-major (``v*S+s``,
    the ``split_chunks_round_robin`` layout); ``x`` is ``[M, mb, ...]``
    stage-0 microbatch inputs; ``aux`` leaves ``[M, ...]``. Grads are
    accumulated in f32 in per-stage microbatch order — the SAME order
    as the lockstep schedule, so loss and grads match it (pinned by
    tests/test_pipeline_async.py).

    Mesh composition (r19): the shard_map spans the FULL mesh, not a
    pp-only one. ``dp`` shards the microbatch rows — ``x_spec`` /
    ``aux_specs`` must name it when dp > 1 (each dp rank then runs the
    schedule on its row shard; the gradient psum over dp is folded
    into the f32 accumulation carry ONCE per accumulator leaf after
    the scan, and loss/ghead are psum'd over dp×pp). ``tp`` shards the
    stage weights per ``stage_specs`` (per-leaf PartitionSpecs over
    the dims AFTER the leading ``V*S`` chunk axis) and the head per
    ``head_specs`` — the stage/head callables are then responsible for
    their own in-body tp collectives (``parallel.mp_ops`` f/g custom
    ops; see models/llama.py ``_tp_local_block``) and must return
    tp-COMPLETE cotangents and gradients (replicated leaves complete
    on every tp rank, sharded leaves shard-local), which the megatron
    f-op placement guarantees. All spec arguments default to the
    pp-only behavior (everything else replicated).

    zb's W ticks consume RING-SAVED residuals: B runs the one
    forward+input-grad backward of its ``jax.vjp`` and stores the
    pullback's own leaves into the residual ring (``slot_r``,
    interval-colored, M-independent depth); W restores the pullback
    and computes weight grads with no second forward replay (~4.5
    work units per microbatch-stage vs the r14 replay's 5 — the
    unused co-outputs of each pullback call are dead code XLA
    eliminates per switch branch).

    ``_schedule`` overrides the built schedule and
    ``_drop_dp_grad_psum`` drops the folded dp gradient psum (tests
    use both to prove mutations trip the analysis passes); everyone
    else leaves them alone.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map

    S, V = int(num_stages), int(virtual_chunks)
    M = x.shape[0]
    if mesh is None or "pp" not in getattr(mesh, "shape", {}):
        raise ValueError("pipeline_train_async needs a mesh with a "
                         "'pp' axis (it is a shard_map program)")
    if mesh.shape["pp"] != S:
        raise ValueError(f"mesh pp axis is {mesh.shape['pp']} but "
                         f"num_stages={S}")
    dp_deg = int(mesh.shape.get("dp", 1))
    busy = {k: int(n) for k, n in mesh.shape.items()
            if k not in ("dp", "tp", "pp") and int(n) > 1}
    if busy:
        raise NotImplementedError(
            f"rank-asymmetric schedules compose dp/tp/pp only; mesh "
            f"axes {busy} must be size 1 (cp/ep inside the per-rank "
            f"op-table scan is future work)")
    if dp_deg > 1:
        aux_leaves = jax.tree_util.tree_leaves(
            aux_specs, is_leaf=lambda v: isinstance(v, P))
        if ("dp" not in _spec_names(x_spec)
                or not aux_leaves
                or not all("dp" in _spec_names(sp)
                           for sp in aux_leaves)):
            raise ValueError(
                "dp > 1 needs x_spec AND aux_specs sharding the "
                "microbatch rows over 'dp' — with replicated inputs "
                "the folded dp gradient psum would over-count by the "
                "dp degree (and global-shaped labels would silently "
                "broadcast against local rows in the head)")
    sched = _schedule if _schedule is not None else build_schedule(
        S, M, V, variant)
    zb = sched.variant == "zb"

    chunks_vs = jax.tree_util.tree_map(
        lambda p: p.reshape((V, S) + p.shape[1:]), stage_params)
    rows_np = dict(
        kind=sched.kind, chunk=sched.chunk, mb=sched.mb,
        slot_x=sched.slot_x, slot_c=sched.slot_c, slot_r=sched.slot_r,
        inject=sched.inject, emit=sched.emit,
        store_up=sched.store_up, store_dn=sched.store_dn)

    is_p = lambda v: isinstance(v, P)
    if stage_specs is None:
        chunk_in_specs: Any = P(None, "pp")
    else:
        chunk_in_specs = jax.tree_util.tree_map(
            lambda sp: P(None, "pp", *tuple(sp)), stage_specs,
            is_leaf=is_p)
    head_in_specs = P() if head_specs is None else head_specs
    x_in_spec = P() if x_spec is None else x_spec
    aux_in_specs = P() if aux_specs is None else aux_specs
    dx_out_spec = P("pp", *tuple(x_in_spec))

    def body(chunks, x_all, aux_all, hp):
        r = lax.axis_index("pp")
        chunks_loc = jax.tree_util.tree_map(
            lambda c: c.reshape((V,) + c.shape[2:]), chunks)
        mb_shape = x_all.shape[1:]
        dt = x_all.dtype
        zero_mb = jnp.zeros(mb_shape, dt)
        rows_all = {k: jnp.asarray(v) for k, v in rows_np.items()}

        # zb residual rings: the pullback of ONE stage vjp is a pytree
        # whose leaves are exactly the residuals W needs — get their
        # avals + treedef abstractly (zero equations traced) so the
        # rings can live in the scan carry and W can rebuild the
        # pullback from a ring slot instead of replaying the forward
        if zb:
            p_abs = jax.tree_util.tree_map(
                lambda c: jax.ShapeDtypeStruct(c.shape[1:], c.dtype),
                chunks_loc)
            pull_abs = jax.eval_shape(
                lambda pp_, xx: jax.vjp(stage_fn, pp_, xx)[1],
                p_abs, jax.ShapeDtypeStruct(mb_shape, dt))
            res_abs, res_tree = jax.tree_util.tree_flatten(pull_abs)
            depth_r = max(int(sched.depth_r), 1)
            sr0 = [jnp.zeros((depth_r,) + l.shape, l.dtype)
                   for l in res_abs]
        else:
            res_tree, sr0 = None, []

        def pick(tree, v):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, v, 0,
                                                   keepdims=False), tree)

        def store_if(buf, val, slot):
            idx = jnp.clip(slot, 0, buf.shape[0] - 1)
            cur = lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                buf, jnp.where(slot >= 0, val, cur), idx, 0)

        def tick(carry, row):
            sx, sc, sr, gacc, ghead, loss, dxbuf = carry
            kind = row["kind"][r]
            v = row["chunk"][r]
            m = jnp.clip(row["mb"][r], 0, M - 1)
            sl_x = row["slot_x"][r]
            sl_c = row["slot_c"][r]
            sl_r = row["slot_r"][r]
            inject = row["inject"][r]
            emit = row["emit"][r]
            p_v = pick(chunks_loc, v)
            x_m = lax.dynamic_index_in_dim(x_all, m, 0, keepdims=False)
            aux_m = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0,
                                                   keepdims=False),
                aux_all)
            x_sl = lax.dynamic_index_in_dim(sx, sl_x, 0, keepdims=False)
            ct_sl = lax.dynamic_index_in_dim(sc, sl_c, 0, keepdims=False)
            x_in = jnp.where(inject == 1, x_m, x_sl)

            def _idle():
                return (sx, sc, sr, zero_mb, zero_mb, gacc, ghead,
                        loss, dxbuf)

            def _f():
                sx2 = lax.dynamic_update_index_in_dim(sx, x_in, sl_x, 0)
                y = stage_fn(p_v, x_in).astype(dt)
                return (sx2, sc, sr, y, zero_mb, gacc, ghead, loss,
                        dxbuf)

            def _b():
                # ONE forward inside the vjp either way; zb ring-saves
                # the pullback's residual leaves so W never replays it
                # (the dp co-output is dead here and DCE'd by XLA)
                _, pull = jax.vjp(stage_fn, p_v, x_in)
                dp, dx = pull(ct_sl)
                if zb:
                    leaves = jax.tree_util.tree_leaves(pull)
                    assert len(leaves) == len(sr), (
                        f"pullback residual structure changed between "
                        f"eval_shape ({len(sr)} leaves) and the B "
                        f"trace ({len(leaves)})")
                    sr2 = [lax.dynamic_update_index_in_dim(rb, l,
                                                           sl_r, 0)
                           for rb, l in zip(sr, leaves)]
                    gacc2 = gacc
                else:
                    sr2 = sr
                    gacc2 = jax.tree_util.tree_map(
                        lambda g, d: g.at[v].add(d.astype(jnp.float32)),
                        gacc, dp)
                dx = dx.astype(dt)
                old = lax.dynamic_index_in_dim(dxbuf, m, 0,
                                               keepdims=False)
                dxbuf2 = lax.dynamic_update_index_in_dim(
                    dxbuf, jnp.where(emit == 1, dx, old), m, 0)
                return (sx, sc, sr2, zero_mb, dx, gacc2, ghead, loss,
                        dxbuf2)

            def _fh():
                sx2 = lax.dynamic_update_index_in_dim(sx, x_in, sl_x, 0)
                y = stage_fn(p_v, x_in).astype(dt)
                loss_m, pull = jax.vjp(
                    lambda hpp, yy: head_fn(hpp, yy, aux_m), hp, y)
                dhead, dout = pull(jnp.ones((), loss_m.dtype))
                sc2 = lax.dynamic_update_index_in_dim(
                    sc, dout.astype(dt), sl_c, 0)
                ghead2 = jax.tree_util.tree_map(
                    lambda g, d: g + d.astype(jnp.float32), ghead, dhead)
                return (sx2, sc2, sr, zero_mb, zero_mb, gacc, ghead2,
                        loss + loss_m.astype(jnp.float32), dxbuf)

            def _w():
                # restore B's pullback from the residual ring: weight
                # grads with NO stage-forward replay (the dx co-output
                # is dead here and DCE'd by XLA)
                leaves = [lax.dynamic_index_in_dim(rb, sl_r, 0,
                                                   keepdims=False)
                          for rb in sr]
                pull = jax.tree_util.tree_unflatten(res_tree, leaves)
                dp, _dx = pull(ct_sl)
                gacc2 = jax.tree_util.tree_map(
                    lambda g, d: g.at[v].add(d.astype(jnp.float32)),
                    gacc, dp)
                return (sx, sc, sr, zero_mb, zero_mb, gacc2, ghead,
                        loss, dxbuf)

            branches = [_idle, _f, _b, _fh] + ([_w] if zb else [])
            (sx, sc, sr, up, dn, gacc, ghead, loss, dxbuf) = lax.switch(
                kind, branches)

            # unconditional neighbour exchange: identical collective
            # signature on every rank, every tick
            up_in = lax.ppermute(
                up, "pp", [(i, (i + 1) % S) for i in range(S)])
            dn_in = lax.ppermute(
                dn, "pp", [(i, (i - 1) % S) for i in range(S)])
            sx = store_if(sx, up_in, row["store_up"][r])
            sc = store_if(sc, dn_in, row["store_dn"][r])
            return (sx, sc, sr, gacc, ghead, loss, dxbuf), None

        carry0 = (
            jnp.zeros((sched.depth_x,) + mb_shape, dt),
            jnp.zeros((sched.depth_c,) + mb_shape, dt),
            sr0,
            jax.tree_util.tree_map(
                lambda c: jnp.zeros(c.shape, jnp.float32), chunks_loc),
            jax.tree_util.tree_map(
                lambda h: jnp.zeros(h.shape, jnp.float32), hp),
            jnp.zeros((), jnp.float32),
            jnp.zeros((M,) + mb_shape, dt),
        )
        (sx, sc, sr, gacc, ghead, loss, dxbuf), _ = lax.scan(
            tick, carry0, rows_all)
        # dp composition: each dp rank accumulated grads for ITS row
        # shard of every microbatch — fold the dp reduction into the
        # f32 accumulators, ONE psum per accumulator leaf (not per
        # microbatch); loss/ghead reduce over dp x pp (pp because only
        # the last rank's head ops are nonzero, as before)
        red_axes = ("pp", "dp") if dp_deg > 1 else ("pp",)
        if dp_deg > 1 and not _drop_dp_grad_psum:
            gacc = jax.tree_util.tree_map(
                lambda g: lax.psum(g, "dp"), gacc)
        loss = lax.psum(loss, red_axes)
        ghead = jax.tree_util.tree_map(
            lambda g: lax.psum(g, red_axes), ghead)
        gacc_out = jax.tree_util.tree_map(
            lambda g: g.reshape((V, 1) + g.shape[1:]), gacc)
        return loss, gacc_out, ghead, dxbuf[None]

    if stage_specs is None:
        gacc_out_specs: Any = P(None, "pp")
    else:
        gacc_out_specs = chunk_in_specs
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(chunk_in_specs, x_in_spec, aux_in_specs,
                  head_in_specs),
        out_specs=(P(), gacc_out_specs, head_in_specs, dx_out_spec),
        check_vma=False)
    loss, gchunks, ghead, dxs = fn(chunks_vs, x, aux, head_params)
    # mean over the M microbatches AND the dp row shards: each dp rank
    # computed per-microbatch means over its rows/dp rows, so the
    # dp-psum'd sums divide by M*dp
    inv_m = 1.0 / (M * dp_deg)
    gchunks = jax.tree_util.tree_map(
        lambda g, p: (g.reshape((V * S,) + g.shape[2:]) * inv_m
                      ).astype(p.dtype),
        gchunks, stage_params)
    ghead = jax.tree_util.tree_map(
        lambda g, p: (g * inv_m).astype(p.dtype), ghead, head_params)
    return loss * inv_m, gchunks, ghead, dxs[0] * inv_m

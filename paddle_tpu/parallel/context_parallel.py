"""Context parallelism: ring attention + Ulysses over the ``cp`` mesh axis.

The reference has no ring attention / context parallel of its own
(SURVEY.md §2.8 CP row: absent; its long-context story is the SEP topology
axis topology.py:204 + sequence-parallel utils + flash-attn varlen
kernels, with the attention alltoall delegated to the model library).
Here long context is first-class:

  - **Ring attention**: each cp rank holds a sequence chunk of q/k/v;
    k/v chunks rotate around the cp ring via ``lax.ppermute`` while each
    hop's partial attention folds into a running online-softmax
    accumulator (m, l, o) — the flash-attention recurrence across
    devices, so the full [T, T] score matrix never exists and sequence
    length scales linearly with cp degree. ppermute rides ICI neighbours.
  - **Ulysses**: ``lax.all_to_all`` re-partitions seq->heads, runs dense/
    pallas flash attention on full sequences for H/cp local heads, and
    all_to_alls back (the alltoall the reference leaves to PaddleNLP).

Both run inside ``shard_map`` and compose with the GSPMD llama forward:
q/k/v arrive [B, T, H, Dh] sharded (dp, cp, tp, -) and the ring runs over
cp only, per tp-local head group.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from .._compat import shard_map

_NEG_INF = -1e30


def _repeat_kv(q, k, v):
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _block_accum(q, k, v, qpos, kpos, causal, sm_scale, m, l, o):
    """Fold one k/v block into the online-softmax state.

    q [B,Tq,H,D]; k/v [B,Tk,Hkv,D] (GQA heads broadcast here, locally,
    so the ring only ever carries the small Hkv chunks);
    m,l [B,H,Tq]; o [B,Tq,H,D] (fp32). qpos/kpos are the GLOBAL token
    positions of the blocks' rows ([Tq]/[Tk] int vectors — arbitrary
    layouts like zigzag welcome).
    """
    k, v = _repeat_kv(q, k, v)
    s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * sm_scale
    if causal:
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(-inf - -inf) -> exp(0)=1 would poison l
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    corr = jnp.exp(m - m_new)
    corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
    l_new = corr * l + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhts,bshd->bthd", p.astype(v.dtype), v).astype(jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def chunk_positions(r, R: int, Tl: int, layout: str = "contiguous"):
    """Global positions of rank ``r``'s local sequence slots.

    contiguous: rank r holds tokens [r*Tl, (r+1)*Tl).
    zigzag: the sequence is cut into 2R cells; rank r holds cell r and
    cell 2R-1-r (one early + one late) — the llama-3 style causal load
    balance: every (rank, hop) pair then carries the same unmasked area
    (tests/test_context_parallel.py proves the count).
    """
    if layout == "zigzag":
        if Tl % 2:
            raise ValueError(
                f"zigzag needs an even per-rank chunk (got {Tl} slots): "
                "the global seq len must be divisible by 2*cp")
        C = Tl // 2
        a = jnp.arange(C)
        return jnp.concatenate([r * C + a, (2 * R - 1 - r) * C + a])
    return r * Tl + jnp.arange(Tl)


def zigzag_global_perm(T: int, R: int) -> np.ndarray:
    """Permutation placing tokens into the zigzag layout: position j of
    the permuted sequence holds original token perm[j]; cp-sharding the
    permuted sequence contiguously gives every rank cell r + cell
    2R-1-r. Host-side (numpy) — it is a static data layout."""
    if T % (2 * R):
        raise ValueError(f"seq len {T} not divisible by 2*cp ({2 * R})")
    C = T // (2 * R)
    out = []
    for r in range(R):
        out.append(np.arange(r * C, (r + 1) * C))
        out.append(np.arange((2 * R - 1 - r) * C, (2 * R - r) * C))
    return np.concatenate(out)


def ring_attention(q, k, v, *, axis_name: str = "cp", causal: bool = True,
                   sm_scale: Optional[float] = None,
                   layout: str = "contiguous"):
    """Blockwise ring attention on per-device chunks (use inside shard_map).

    q/k/v are the LOCAL sequence chunks [B, T/cp, H|Hkv, Dh]; returns the
    local output chunk [B, T/cp, H, Dh]. The ring rotates the UNREPEATED
    Hkv-head k/v chunks (GQA broadcast happens per-hop inside
    _block_accum), so ppermute bandwidth is Hkv/H of the naive version.

    ``layout``: how local slots map to global positions (chunk_positions).
    contiguous causal rings are imbalanced — late ranks own almost-fully
    unmasked hops while early ranks mask almost everything; "zigzag"
    gives every rank one head + one tail cell so each hop's unmasked
    area is equal across ranks (the reference has no CP at all; this is
    the standard fix from ring-flash-attention / llama-3 training).
    """
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    R = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    qpos = chunk_positions(r, R, Tl, layout)

    m0 = jnp.full((B, H, Tl), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    o0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    fwd = [(i, (i + 1) % R) for i in range(R)]

    def step(carry, s):
        k_c, v_c, m, l, o = carry
        src = (r - s) % R                     # origin rank of this kv chunk
        m, l, o = _block_accum(q, k_c, v_c, qpos,
                               chunk_positions(src, R, Tl, layout),
                               causal, sm_scale, m, l, o)
        k_c = lax.ppermute(k_c, axis_name, fwd)
        v_c = lax.ppermute(v_c, axis_name, fwd)
        return (k_c, v_c, m, l, o), None

    # R-1 hops rotate; the final block needs no further ppermute
    (k_c, v_c, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                      jnp.arange(R - 1))
    src_last = (r - (R - 1)) % R
    m, l, o = _block_accum(q, k_c, v_c, qpos,
                           chunk_positions(src_last, R, Tl, layout),
                           causal, sm_scale, m, l, o)
    l = jnp.where(l == 0.0, 1.0, l)           # rows with nothing to attend
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "cp",
                      causal: bool = True, sm_scale: Optional[float] = None,
                      impl: str = "auto"):
    """Ulysses sequence parallelism (use inside shard_map): all_to_all
    seq<->heads so each cp rank attends the FULL sequence for H/cp heads,
    then redistributes. The cp degree must divide the (local) head counts,
    both H and Hkv — GQA k/v stay unrepeated through the all_to_all
    (flash_attention broadcasts them natively)."""
    R = lax.psum(1, axis_name)
    if q.shape[2] % R or k.shape[2] % R:
        raise ValueError(
            f"ulysses needs cp degree {R} to divide local head counts "
            f"H={q.shape[2]}, Hkv={k.shape[2]}")
    # [B, T/cp, H, D] -> [B, T, H/cp, D]
    a2a = partial(lax.all_to_all, axis_name=axis_name, split_axis=2,
                  concat_axis=1, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    from ..ops.pallas.flash_attention import flash_attention
    out = flash_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale,
                          impl=impl)
    # back: [B, T, H/cp, D] -> [B, T/cp, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def context_parallel_attention(q, k, v, mesh: Mesh, *, impl: str = "ring",
                               causal: bool = True,
                               sm_scale: Optional[float] = None):
    """Global-array entry: q/k/v [B, T, H, Dh] with T sharded over ``cp``
    (and optionally B over dp, H over tp); returns same layout.

    Wraps ring/ulysses in shard_map over every mesh axis that shards an
    input dim, so it drops into a GSPMD forward (models/llama.py).
    """
    fns = {"ring": ring_attention, "ulysses": ulysses_attention,
           "zigzag": partial(ring_attention, layout="zigzag")}
    fn = fns[impl]
    dp = "dp" if "dp" in mesh.shape else None
    tp = "tp" if "tp" in mesh.shape else None
    spec = P(dp, "cp", tp, None)

    inner = partial(fn, axis_name="cp", causal=causal, sm_scale=sm_scale)
    return shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)

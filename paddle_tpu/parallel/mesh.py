"""Hybrid-parallel topology over a jax.sharding.Mesh.

Redesign of the reference's ``HybridCommunicateGroup``
(python/paddle/distributed/fleet/base/topology.py:70,189-238): the reference
builds one NCCL communicator per parallelism axis (data/pipe/sharding/sep/
model); here the same topology is expressed as ONE device mesh with named
axes, and per-axis "groups" are simply the mesh axis names used in
PartitionSpecs / collective calls. XLA GSPMD then emits the collectives so
they ride ICI neighbours instead of host networking.

Axis naming convention (matching fleet's order topology.py:189):
  - ``dp``   data parallel (batch sharding; also ZeRO/sharding axis)
  - ``pp``   pipeline parallel (stage sharding)
  - ``tp``   tensor/model parallel (megatron TP; sequence parallel
             reuses this axis, as megatron-SP does in the reference's
             sequence_parallel_utils.py)
  - ``ep``   expert parallel (own physical axis when >1; MoE all_to_all)
  - ``cp``   context parallel (sequence dim; the reference's SEP axis,
             topology.py:204 — ring attention / Ulysses ride this)
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_GLOBAL_MESH: Optional["HybridMesh"] = None


@dataclasses.dataclass
class HybridMesh:
    """A named-axis device mesh + the fleet-style degree bookkeeping.

    ``mesh`` is the jax Mesh; the ``*_degree`` properties mirror the
    reference's ``HybridCommunicateGroup.get_*_parallel_world_size`` API
    surface (topology.py:262-331) so user code can query the topology the
    same way.
    """

    mesh: Mesh

    # -- degrees ------------------------------------------------------------
    def degree(self, axis: str) -> int:
        return self.mesh.shape.get(axis, 1)

    @property
    def dp_degree(self) -> int:
        return self.degree("dp")

    @property
    def pp_degree(self) -> int:
        return self.degree("pp")

    @property
    def tp_degree(self) -> int:
        return self.degree("tp")

    @property
    def ep_degree(self) -> int:
        return self.degree("ep")

    @property
    def cp_degree(self) -> int:
        return self.degree("cp")

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    # -- sharding helpers ---------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def __enter__(self):
        self._ctx = self.mesh.__enter__()
        return self

    def __exit__(self, *exc):
        return self.mesh.__exit__(*exc)


def init_hybrid_mesh(
    dp: int = 1,
    pp: int = 1,
    tp: int = 1,
    ep: int = 1,
    cp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    set_global: bool = True,
) -> HybridMesh:
    """Build the hybrid mesh, fleet's ``fleet.init(strategy)`` equivalent.

    Axis order is (dp, pp[, cp][, ep], tp) — tp innermost so tensor
    collectives ride nearest-neighbour ICI links, dp outermost (its
    all-reduce tolerates the longer hops / DCN), matching the layout intent
    of the reference's order (topology.py:189 'data','pipe','sharding',
    'sep','model' — model innermost). ``need = dp*pp*tp*ep*cp`` devices.

    ``ep`` (expert parallel) and ``cp`` (context parallel, the reference's
    SEP axis topology.py:204) only materialise as mesh axes when their
    degree > 1, so PartitionSpecs written against dp/pp/tp are unaffected.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = dp * pp * tp * ep * cp
    if len(devices) < need:
        raise ValueError(
            f"mesh dp*pp*tp*ep*cp={need} exceeds available devices "
            f"{len(devices)}")
    shape, names = [dp, pp], ["dp", "pp"]
    if cp > 1:
        shape.append(cp)
        names.append("cp")
    if ep > 1:
        shape.append(ep)
        names.append("ep")
    shape.append(tp)
    names.append("tp")
    arr = np.array(devices[:need]).reshape(shape)
    mesh = Mesh(arr, axis_names=tuple(names))
    hm = HybridMesh(mesh=mesh)
    if set_global:
        global _GLOBAL_MESH
        _GLOBAL_MESH = hm
    return hm


def get_hybrid_mesh() -> Optional[HybridMesh]:
    return _GLOBAL_MESH


def mesh_axis_size(axis: str) -> int:
    hm = get_hybrid_mesh()
    return hm.degree(axis) if hm is not None else 1

"""1F1B and interleaved (VPP) pipeline schedules, traced SPMD-style.

TPU-native counterpart of the reference's schedule library
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:565
``forward_backward_pipeline`` = 1F1B, :1372 interleaved VPP, and
pipeline_zero_bubble.py): there, per-rank Python loops issue NCCL
isend/irecv and hold explicit activation queues. Here the entire
schedule is ONE traced ``lax.scan`` whose carried buffers have a
``pp``-sharded stage axis, so the per-tick neighbour exchange lowers to
an XLA ``collective_permute`` over the ICI ring.

Why not ``jax.grad`` through the GPipe scan (parallel/pipeline_spmd.py)?
Autodiff of a scan replays ALL forward iterations, then ALL backward
iterations — the GPipe memory profile: every stage holds residuals for
all M microbatches (O(M) activation memory). The defining property of
1F1B is that a microbatch's backward starts as soon as its forward
leaves the last stage, bounding each stage's live activations at O(S)
regardless of M. That cannot be expressed *through* autodiff of the
forward schedule; it must be written as an explicit fused
forward+backward program. This module does that with per-stage
``jax.vjp`` calls inside the scan body (stage recompute in backward =
the reference's recompute pass; per-layer remat inside ``stage_fn``
still applies and bounds the recompute's own peak).

Schedule layout (S pipeline slots, M microbatches, tick t = scan step):
  fwd   : slot s computes microbatch m = t - s
  head  : loss head runs on microbatch m = t - (S-1) as it exits
  bwd   : slot s back-props microbatch m = t - (2S-1) + s
  total : M + 2S - 1 ticks; each tick every slot does one fwd AND one
          bwd (on different microbatches) — the steady state of 1F1B.
Stage inputs live in a circular buffer of depth 2S (the lifetime of a
saved input is 2(S-s)-1 ticks), which is the O(S)-not-O(M) bound
(tests/test_pipeline_1f1b.py compares compiled peak memory vs GPipe).

Zero-bubble (reference pipeline_zero_bubble.py, ZB-H1/H2): splits each
backward into B (input-grad, on the critical path) and W (weight-grad,
not), and schedules W into the fill/drain bubbles of each RANK. That
lever does not exist in this lockstep traced form: every tick every
device executes the same program (one fwd + one bwd per slot via vmap),
so there are no idle rank-ticks to fill — the bubble manifests as the
(2S-1)/(M+2S-1) fraction of ticks whose microbatch slot is masked out.
Deferring W here would have to re-derive the pullback (an extra forward
per slot-microbatch, cost M*F) to save only (2S-1)*W of masked work — a
net loss for any M > 2S. The equivalent levers under XLA are: raise M
(amortizes the fixed bubble), VPP (below, for partition parity), and
remat inside stage_fn (frees the memory that would have bought ZB-H2's
schedule). This is a deliberate redesign decision, not an omission.

Interleaved VPP (``virtual_chunks=V > 1``): the layer stack is split
into V*S chunks and chunk v*S+s is placed on device s (round-robin,
exactly the reference's VPP partitioning) by laying the slot axis out
as ``[V, S]`` with only the second dim pp-sharded. The ring wraps: a
microbatch leaving chunk (v, S-1) re-enters chunk (v+1, 0). Honest
note on cost: in a lockstep traced program every device computes its V
chunks every tick, so VPP here does NOT shrink the fill bubble the way
the reference's per-rank dispatch does (it cannot skip idle chunks);
what it preserves is the reference's model partitioning (parameter
round-robin for checkpoint/layout parity) and the 1F1B memory bound.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def schedule_ticks(num_stages: int, num_microbatches: int,
                   virtual_chunks: int = 1,
                   schedule: str = "lockstep") -> int:
    """Trip count of the schedule scan. This is the ONE definition —
    the executors size their scans with it and the
    collective-consistency lint checks the traced scan against it, so a
    schedule edit that changes the tick arithmetic cannot silently
    desynchronize the two.

    ``schedule``:
      * ``"lockstep"`` — the traced all-slots-every-tick form of this
        module: ``M + 2·S·V - 1`` ticks (fill + steady + drain).
      * ``"1f1b"`` — rank-asymmetric 1F1B (pipeline_async):
        ``2·(V·M + S - 1)`` half-step ticks, the reference per-rank
        1F1B span (interleaved V>1 included).
      * ``"zb"`` — ZB-H1-style W-deferral (pipeline_async, V=1):
        ``3·M + S - 1`` for M >= S; fill-dominated below that — the
        count comes from the validated schedule builder either way.
    """
    S = int(num_stages) * int(virtual_chunks)
    M = int(num_microbatches)
    if schedule == "lockstep":
        return M + 2 * S - 1
    from .pipeline_async import build_schedule
    return build_schedule(int(num_stages), M, int(virtual_chunks),
                          schedule).ticks


def _tree_zeros_f32(t):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), t)


def _tree_add(a, b):
    return jax.tree_util.tree_map(
        lambda x, y: x + y.astype(x.dtype), a, b)


def _tree_scale_cast(t, s, like):
    return jax.tree_util.tree_map(
        lambda x, l: (x * s).astype(l.dtype), t, like)


def pipeline_train_1f1b(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array, Any], jax.Array],
    stage_params: Any,
    head_params: Any,
    x: jax.Array,
    aux: Any,
    *,
    num_stages: int,
    virtual_chunks: int = 1,
    mesh=None,
    mb_spec: Optional[P] = None,
):
    """One fused forward+backward pass under the 1F1B schedule.

    Args:
      stage_fn: ``(chunk_params, x_mb) -> y_mb`` for one chunk (= one
        stage when ``virtual_chunks == 1``).
      head_fn: ``(head_params, y_mb, aux_mb) -> scalar`` per-microbatch
        loss (mean over the microbatch's tokens).
      stage_params: pytree with leading dim ``num_stages*virtual_chunks``
        ordered chunk-major ``v*S + s`` (shard over pp; see
        ``split_chunks_round_robin`` for the [V,S] layout helper).
      head_params: pytree for the loss head (final norm / lm_head / ...).
      x: ``[M, mb, ...]`` microbatched stage-0 inputs (already embedded).
      aux: pytree of per-microbatch extras (labels), leaves ``[M, ...]``.
      mesh/mb_spec: when given, stage buffers get
        ``with_sharding_constraint`` to ``P(("pp",) + mb_spec)`` laid out
        round-robin for VPP.

    Returns ``(loss, grads_stage_params, grads_head_params, dx)``:
    ``loss`` is the mean over microbatches; grads are averaged the same
    way (accumulated in f32, cast back to param dtype); ``dx`` is
    ``[M, mb, ...]`` — the cotangent of ``x`` for the embedding pullback.
    """
    V = virtual_chunks
    S_dev = num_stages
    S = S_dev * V  # virtual pipeline depth (slots)
    M = x.shape[0]
    if stage_params is None or M < 1:
        raise ValueError("need stage_params and at least 1 microbatch")
    R = 2 * S  # circular saved-input buffer depth
    mb_shape = x.shape[1:]

    def constrain(t):
        """Shard the slot axis round-robin over pp: [V*S_dev, ...] viewed
        as [V, S_dev, ...] with the device dim sharded."""
        if mesh is None or mb_spec is None:
            return t
        extra = t.ndim - 1 - len(mb_spec)
        spec = P(None, "pp", *mb_spec, *([None] * extra))
        vs = t.reshape((V, S_dev) + t.shape[1:])
        vs = lax.with_sharding_constraint(vs, NamedSharding(mesh, spec))
        return vs.reshape(t.shape)

    def stage_bwd(p_s, x_in, ct):
        _, pull = jax.vjp(stage_fn, p_s, x_in)
        dp, dx = pull(ct)
        return dp, dx

    def shift_ring(state, inject):
        """slot k takes slot k-1's value; slot 0 takes ``inject``.
        With the [V, S_dev] round-robin layout this is a
        collective_permute between neighbouring devices at chunk
        boundaries and a local move otherwise."""
        return jnp.concatenate([inject[None], state[:-1]], axis=0)

    fstate0 = jnp.zeros((S,) + mb_shape, x.dtype)
    bstate0 = jnp.zeros((S,) + mb_shape, x.dtype)
    saved0 = jnp.zeros((S, R) + mb_shape, x.dtype)
    gacc0 = _tree_zeros_f32(stage_params)
    ghead0 = _tree_zeros_f32(head_params)

    def tick(carry, t):
        fstate, bstate, saved, gacc, ghead, loss_acc = carry

        # ---- forward: slot s consumes microbatch t-s -------------------
        m_in = t
        x_next = lax.dynamic_index_in_dim(
            x, jnp.clip(m_in, 0, M - 1), 0, keepdims=False)
        x_in = jnp.where(m_in < M, x_next, jnp.zeros_like(x_next))
        fin = constrain(shift_ring(fstate, x_in))
        # save this tick's slot inputs: slot s -> ring slot (t - s) mod R
        slots = jnp.mod(t - jnp.arange(S), R)
        saved = jax.vmap(
            lambda buf, idx, val: lax.dynamic_update_index_in_dim(
                buf, val, idx, 0))(saved, slots, fin)
        fstate = constrain(jax.vmap(stage_fn)(stage_params, fin))

        # ---- loss head on the microbatch exiting the last slot ---------
        m_h = t - (S - 1)
        head_valid = jnp.logical_and(m_h >= 0, m_h < M)
        aux_mh = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(
                a, jnp.clip(m_h, 0, M - 1), 0, keepdims=False), aux)
        loss_m, head_pull = jax.vjp(
            lambda hp, y: head_fn(hp, y, aux_mh), head_params, fstate[-1])
        dhead, dout = head_pull(
            jnp.where(head_valid, 1.0, 0.0).astype(loss_m.dtype))
        loss_acc = loss_acc + jnp.where(head_valid, loss_m, 0.0)
        ghead = _tree_add(ghead, dhead)

        # ---- backward: slot s back-props microbatch t-(2S-1)+s ---------
        # bstate[s] holds the cotangent produced last tick by slot s+1
        # (or, for the top slot, the head's dout from last tick's exit).
        bwd_x = jax.vmap(
            lambda buf, idx: lax.dynamic_index_in_dim(
                buf, idx, 0, keepdims=False))(
            saved, jnp.mod(t - (2 * S - 1) + jnp.arange(S), R))
        dparams, dxs = jax.vmap(stage_bwd)(stage_params, bwd_x, bstate)
        gacc = _tree_add(gacc, dparams)
        bstate = constrain(
            jnp.concatenate([dxs[1:], dout[None].astype(x.dtype)], axis=0))
        return ((fstate, bstate, saved, gacc, ghead, loss_acc),
                dxs[0])  # stage-0 dx stream

    carry0 = (fstate0, bstate0, saved0, gacc0, ghead0,
              jnp.zeros((), jnp.float32))
    (carry_out, dx_stream) = lax.scan(
        tick, carry0, jnp.arange(schedule_ticks(S_dev, M, V)))
    _, _, _, gacc, ghead, loss_sum = carry_out

    # stage-0 dx for microbatch m emerges at tick m + (2S-1)
    dx = dx_stream[2 * S - 1:]
    inv_m = 1.0 / M
    return (loss_sum * inv_m,
            _tree_scale_cast(gacc, inv_m, stage_params),
            _tree_scale_cast(ghead, inv_m, head_params),
            dx * inv_m)


def split_chunks_round_robin(layer_params, num_layers: int,
                             num_stages: int, virtual_chunks: int = 1):
    """[L, ...] stacked layers -> [V*S, L/(V*S), ...] chunk-major order
    (chunk k = v*S + s holds layers [k*L/(VS), ...)) — the reference's
    VPP round-robin model partition (pipeline_parallel.py:1372)."""
    VS = num_stages * virtual_chunks
    if num_layers % VS:
        raise ValueError(f"layers {num_layers} not divisible by "
                         f"stages*chunks {VS}")
    return jax.tree_util.tree_map(
        lambda p: p.reshape((VS, num_layers // VS) + p.shape[1:]),
        layer_params)


def schedule_efficiency(num_stages: int, num_microbatches: int,
                        virtual_chunks: int = 1,
                        schedule: str = "lockstep") -> float:
    """Useful-work fraction of a pipeline schedule — the analytic model
    measured efficiency is asserted against in tests.

    ``schedule="lockstep"`` (this module's traced form): the schedule
    runs ``M + 2S - 1`` lockstep ticks and every tick executes all S
    slots (masked work included — an SPMD traced program cannot skip a
    slot), so efficiency = M / (M + 2S - 1). VPP does not enter: every
    device computes its V chunks every tick (module docstring), so V
    multiplies useful and wasted work alike.
    tests/test_pipeline_1f1b.py checks the compiled step's XLA flop
    count against this prediction.

    ``schedule="1f1b"`` (rank-asymmetric, pipeline_async): ticks are
    half-steps (one F or one full backward per rank per tick), span
    ``2(VM + S - 1)``, efficiency ``VM / (VM + S - 1)`` — exactly the
    reference 1F1B bubble ``1 - (S-1)/(VM + S - 1)``, interleaved V>1
    included (the closed form is pinned against the schedule builder
    across a (S, M, V) grid in tests/test_pipeline_async.py).

    ``schedule="zb"`` (ZB-H1 W-deferral, V=1): each microbatch is
    three unit ops per rank (F, input-grad B, deferred weight-grad W);
    efficiency = 3M / ticks with the tick count from the validated
    builder (= 3M/(3M + S - 1) for M >= S) — strictly above the 1F1B
    bound at every geometry. Tick-fraction efficiency; the W split's
    extra recompute FLOPs are documented in docs/PERF.md.
    """
    S, M = int(num_stages), int(num_microbatches)
    V = int(virtual_chunks)
    if S < 1 or M < 1:
        raise ValueError("num_stages and num_microbatches must be >= 1")
    if schedule == "lockstep":
        return M / (M + 2 * S - 1)
    if schedule == "1f1b":
        # same validity envelope as the builder, so the model can never
        # quote an efficiency for a schedule that does not build
        from .pipeline_async import build_schedule
        build_schedule(S, M, V, "1f1b")
        return V * M / (V * M + S - 1)
    if schedule == "zb":
        ticks = schedule_ticks(S, M, V, schedule="zb")
        return 3 * V * M / ticks
    raise ValueError(f"schedule must be 'lockstep', '1f1b' or 'zb', "
                     f"got {schedule!r}")

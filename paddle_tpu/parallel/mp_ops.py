"""Manual tensor-parallel collective ops for shard_map stage bodies.

TPU-native counterpart of the reference's mp_ops.py identity/all-reduce
pair (``_mp_allreduce`` / ``c_identity``, the megatron "f"/"g"
operators): under GSPMD the compiler inserts these from sharding
annotations, but inside a ``shard_map`` body — where the async pipeline
schedules run their per-rank op tables — collectives are MANUAL, and
``jax.vjp`` *inside* the body transposes a raw ``lax.psum`` to another
``psum`` (measured on jax 0.4.37: an in-body pullback through a bare
psum over-counts by the axis size; differentiating *through* the
shard_map boundary is rewritten correctly, but the pipeline executors
call ``jax.vjp`` per tick inside the body). These two ops pin the
correct pair with ``custom_vjp``:

  * :func:`psum_fwd_identity_bwd` — megatron "g": all-reduce in
    forward (the row-parallel matmul's partial sums), identity in
    backward (each rank's partial contributed linearly with
    coefficient 1, so the cotangent passes through once).
  * :func:`identity_fwd_psum_bwd` — megatron "f": identity in forward
    (the replicated stream enters column-parallel weights), all-reduce
    in backward (each rank's column shard contributes a partial input
    cotangent; the sum re-completes it — and every replicated weight
    consumed *upstream* of this op therefore receives a COMPLETE
    gradient, which is why the executor never tp-psums grad
    accumulators).

Both are identity at axis size 1 (the psum is a no-op), so callers can
apply them unconditionally on any mesh that names the axis.
"""
from __future__ import annotations

from functools import partial

import jax


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_fwd_identity_bwd(x, axis_name: str):
    """All-reduce ``x`` over ``axis_name``; backward is identity.

    Use after a ROW-parallel matmul (megatron "g"): the forward value
    is a partial sum per rank, the completed activation's cotangent
    flows back to each rank's partial exactly once."""
    return jax.lax.psum(x, axis_name)


def _g_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _g_bwd(axis_name, _res, ct):
    return (ct,)


psum_fwd_identity_bwd.defvjp(_g_fwd, _g_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def identity_fwd_psum_bwd(x, axis_name: str):
    """Identity forward; all-reduce the cotangent over ``axis_name``.

    Use where a tp-REPLICATED stream feeds column-parallel weights
    (megatron "f"): each rank back-propagates a partial input
    cotangent through its own column shard; the backward psum
    re-completes it before it reaches the residual stream (and any
    replicated weights upstream)."""
    return x


def _f_fwd(x, axis_name):
    return x, None


def _f_bwd(axis_name, _res, ct):
    return (jax.lax.psum(ct, axis_name),)


identity_fwd_psum_bwd.defvjp(_f_fwd, _f_bwd)

"""SPMD pipeline parallelism inside one jitted program.

TPU-native redesign of the reference's microbatch pipeline schedules
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:565
``forward_backward_pipeline`` and pp_utils/p2p_communication.py isend/irecv):
instead of per-rank Python schedule loops exchanging activations over NCCL
p2p, the whole pipeline is ONE traced computation. A buffer of per-stage
microbatch states carries the leading ``pp``-sharded stage axis; shifting the
buffer by one slot each step lowers to an XLA ``collective_permute`` over the
ICI ring, and every stage's compute runs concurrently inside a single
``lax.scan`` step (the GPipe schedule; fill/drain bubbles included).

Because the schedule is traced, ``jax.grad`` through it yields the reverse
pipeline automatically — the backward bubble mirrors forward, which is what
the reference's hand-written 1F1B achieves by interleaving; XLA's scheduler
overlaps the permute with compute.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(params_list):
    """Stack per-stage pytrees into one pytree with a leading stage axis
    (shard it with PartitionSpec('pp', ...))."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_spmd(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    num_stages: int,
    remat: bool = True,
):
    """Run ``x``'s microbatches through ``num_stages`` pipeline stages.

    Args:
      stage_fn: ``(params_s, state) -> state`` for ONE stage; vmapped over
        the stage axis so every stage computes concurrently.
      stage_params: pytree whose leaves have leading dim ``num_stages``
        (see stack_stage_params); shard that axis over the mesh's ``pp``.
      x: ``[M, mb, ...]`` microbatched input (M = number of microbatches).
      remat: rematerialise stage activations in the backward pass
        (the reference's recompute pass; trades FLOPs for HBM).

    Returns ``[M, mb, ...]`` outputs, each having passed through all stages.
    """
    S = num_stages
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    stages_step = jax.vmap(stage_fn)  # over the stage axis

    # state buffer: slot s holds the microbatch currently inside stage s
    state0 = jnp.zeros((S,) + x.shape[1:], dtype=x.dtype)
    # pad the input schedule with drain-phase dummies
    pad = jnp.zeros((S - 1,) + x.shape[1:], dtype=x.dtype) if S > 1 else x[:0]
    feed = jnp.concatenate([x, pad], axis=0) if S > 1 else x

    def step(state, x_t):
        # shift: stage s takes stage s-1's previous output; stage 0 ingests
        # the next microbatch. On a pp-sharded buffer this concatenate+slice
        # is a collective_permute over neighbouring stages.
        state = jnp.concatenate([x_t[None], state[:-1]], axis=0)
        state = stages_step(stage_params, state)
        return state, state[-1]

    _, ys = lax.scan(step, state0, feed)
    return ys[S - 1:]  # first S-1 emissions are fill-phase garbage


def microbatch(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...] (reference: PipelineParallel micro-batching
    of the global batch, pipeline_parallel.py:810 train_batch)."""
    B = x.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by {num_microbatches}")
    return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])

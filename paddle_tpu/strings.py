"""String tensor ops (reference: paddle/phi/kernels/strings/ —
StringTensor with strings_lower_upper_kernel, strings_copy, plus the
phi/api strings_api_gen surface paddle._C_ops.strings_*).

Honest TPU position: strings never touch the accelerator — in the
reference too, string kernels are CPU-only pre/post-processing next to
the tokenizer. So the storage here is a numpy object array on host, and
the contract is the API: creation, lower/upper (with the reference's
use_utf8_encoding switch — False = ASCII-only fast path), equality, and
conversion to/from the numeric token tensors that DO go to the chip.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "lower", "upper", "equal",
           "encode_utf8", "decode_utf8"]


class StringTensor:
    """Host-resident string array (reference phi::StringTensor,
    paddle/phi/core/string_tensor.h)."""

    def __init__(self, data: Union[np.ndarray, Sequence[str]]):
        arr = np.asarray(data, dtype=object)
        bad = [x for x in arr.ravel() if not isinstance(x, str)]
        if bad:
            raise TypeError(f"StringTensor holds str only, got {type(bad[0])}")
        self._data = arr

    @property
    def shape(self):
        return tuple(self._data.shape)

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data.tolist()!r})"


def to_string_tensor(data) -> StringTensor:
    return data if isinstance(data, StringTensor) else StringTensor(data)


def _map(x: StringTensor, fn) -> StringTensor:
    return StringTensor(np.vectorize(fn, otypes=[object])(x._data))


def lower(x, use_utf8_encoding: bool = False) -> StringTensor:
    """strings_lower (strings_lower_upper_kernel.h): ASCII tolower by
    default; full unicode casefold when use_utf8_encoding."""
    x = to_string_tensor(x)
    if use_utf8_encoding:
        return _map(x, str.lower)
    return _map(x, lambda s: "".join(
        chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s))


def upper(x, use_utf8_encoding: bool = False) -> StringTensor:
    x = to_string_tensor(x)
    if use_utf8_encoding:
        return _map(x, str.upper)
    return _map(x, lambda s: "".join(
        chr(ord(c) - 32) if "a" <= c <= "z" else c for c in s))


def equal(x, y) -> np.ndarray:
    return to_string_tensor(x)._data == to_string_tensor(y)._data


def _truncate_utf8(b: bytes, limit: int) -> bytes:
    """Cut at <= limit bytes WITHOUT splitting a multi-byte character:
    back off over UTF-8 continuation bytes (0b10xxxxxx) and the lead
    byte they belong to."""
    if len(b) <= limit:
        return b
    end = limit
    while end > 0 and (b[end] & 0xC0) == 0x80:
        end -= 1
    return b[:end]


def encode_utf8(x, maxlen: Optional[int] = None, pad: int = 0):
    """StringTensor -> padded uint8 Tensor [n, maxlen] + lengths — the
    bridge onto the chip (device tensors are numeric). Truncation at
    ``maxlen`` lands on a character boundary so every row stays
    decodable."""
    from .core.tensor import Tensor
    import jax.numpy as jnp
    x = to_string_tensor(x)
    raw: List[bytes] = [s.encode("utf-8") for s in x._data.ravel()]
    L = (max((len(b) for b in raw), default=0) if maxlen is None
         else int(maxlen))
    buf = np.full((len(raw), L), pad, np.uint8)
    lens = np.zeros((len(raw),), np.int32)
    for i, b in enumerate(raw):
        b = _truncate_utf8(b, L)
        buf[i, :len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(b)
    return Tensor(jnp.asarray(buf)), Tensor(jnp.asarray(lens))


def decode_utf8(codes, lengths) -> StringTensor:
    buf = np.asarray(codes.data if hasattr(codes, "data") else codes,
                     np.uint8)
    lens = np.asarray(lengths.data if hasattr(lengths, "data") else lengths,
                      np.int64)
    return StringTensor([bytes(buf[i, :lens[i]]).decode("utf-8")
                         for i in range(buf.shape[0])])

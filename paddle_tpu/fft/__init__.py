"""paddle.fft namespace (reference: python/paddle/fft.py — jnp.fft carries
the math; XLA lowers FFTs natively on TPU)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def _u(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _w(fn):
    def wrapped(x, *args, **kwargs):
        kwargs.pop("name", None)
        return Tensor(fn(_u(x), *args, **kwargs))
    wrapped.__name__ = fn.__name__
    return wrapped


fft = _w(jnp.fft.fft)
ifft = _w(jnp.fft.ifft)
fft2 = _w(jnp.fft.fft2)
ifft2 = _w(jnp.fft.ifft2)
fftn = _w(jnp.fft.fftn)
ifftn = _w(jnp.fft.ifftn)
rfft = _w(jnp.fft.rfft)
irfft = _w(jnp.fft.irfft)
rfft2 = _w(jnp.fft.rfft2)
irfft2 = _w(jnp.fft.irfft2)
rfftn = _w(jnp.fft.rfftn)
irfftn = _w(jnp.fft.irfftn)
hfft = _w(jnp.fft.hfft)
ihfft = _w(jnp.fft.ihfft)


def _hermitian_nd(base_1d, last_fn, x, s=None, axes=None, norm="backward",
                  name=None):
    """hfft2/hfftn-style transforms: full FFT over all axes but the
    last, hermitian transform on the last (reference fft.py hfftn).
    For the inverse family the hermitian step runs FIRST — its input
    must be real (rfft under the hood); the separable axes commute."""
    d = _u(x)
    nd = d.ndim
    if axes is None:
        # paddle semantics: with s given, transform the LAST len(s) axes
        n_axes = nd if s is None else len(s)
        axes = tuple(range(nd - n_axes, nd))
    else:
        axes = tuple(a % nd for a in axes)
    head, last = axes[:-1], axes[-1]
    n_last = None if s is None else s[-1]
    s_head = None if s is None else s[:-1]
    if base_1d == "h":
        if head:
            d = jnp.fft.fftn(d, s=s_head, axes=head, norm=norm)
        out = last_fn(d, n=n_last, axis=last, norm=norm)
    else:
        out = last_fn(d, n=n_last, axis=last, norm=norm)
        if head:
            out = jnp.fft.ifftn(out, s=s_head, axes=head, norm=norm)
    return Tensor(out)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _hermitian_nd("h", jnp.fft.hfft, x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hermitian_nd("h", jnp.fft.hfft, x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _hermitian_nd("i", jnp.fft.ihfft, x, s, axes, norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hermitian_nd("i", jnp.fft.ihfft, x, s, axes, norm)
fftshift = _w(jnp.fft.fftshift)
ifftshift = _w(jnp.fft.ifftshift)


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)

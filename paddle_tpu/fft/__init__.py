"""paddle.fft namespace (reference: python/paddle/fft.py — jnp.fft carries
the math; XLA lowers FFTs natively on TPU)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def _u(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _w(fn):
    def wrapped(x, *args, **kwargs):
        kwargs.pop("name", None)
        return Tensor(fn(_u(x), *args, **kwargs))
    wrapped.__name__ = fn.__name__
    return wrapped


fft = _w(jnp.fft.fft)
ifft = _w(jnp.fft.ifft)
fft2 = _w(jnp.fft.fft2)
ifft2 = _w(jnp.fft.ifft2)
fftn = _w(jnp.fft.fftn)
ifftn = _w(jnp.fft.ifftn)
rfft = _w(jnp.fft.rfft)
irfft = _w(jnp.fft.irfft)
rfft2 = _w(jnp.fft.rfft2)
irfft2 = _w(jnp.fft.irfft2)
rfftn = _w(jnp.fft.rfftn)
irfftn = _w(jnp.fft.irfftn)
hfft = _w(jnp.fft.hfft)
ihfft = _w(jnp.fft.ihfft)
fftshift = _w(jnp.fft.fftshift)
ifftshift = _w(jnp.fft.ifftshift)


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)

"""Qwen2-MoE-family decoder: Llama attention + MoE FFN with shared expert.

Capability target: the reference ecosystem's MoE pretrain path —
python/paddle/incubate/distributed/models/moe/moe_layer.py (dispatch) +
fused cutlass MoE kernels — redesigned as one jitted SPMD program.

Parallelism (on top of models/llama.py's tp/sp/dp):
  - EP: expert weights carry a leading E axis sharded over the mesh ``ep``
    axis; the dense dispatch einsums (incubate.moe.functional) compile to
    the expert all_to_all under GSPMD.
  - The router and shared expert stay tp-sharded like llama's MLP.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..incubate.moe.functional import moe_ffn
from .llama import _mm, rms_norm, rope


def _dense_w(w, dtype):
    """Dense view of a weight that may be an Int8Weight: the einsum-
    dispatched MoE FFN consumes full expert tensors, so quantized
    experts are dequantized here and XLA fuses the int8→dtype cast +
    per-channel scale into the dispatch einsums (the HBM read — the
    thing int8 halves — is still of the int8 buffer)."""
    return w.dequant(dtype) if hasattr(w, "dequant") else w


@dataclasses.dataclass
class Qwen2MoeConfig:
    vocab_size: int = 151936
    hidden_size: int = 2048
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1000000.0
    # MoE
    num_experts: int = 60
    num_experts_per_tok: int = 4
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    capacity_factor: float = 2.0
    router_aux_loss_coef: float = 0.001
    # "einsum": GShard capacity dispatch (drops overflow tokens; the
    # all_to_all EP path). "dropless": the authored grouped-GEMM Pallas
    # kernel (ops/pallas/grouped_matmul.py) — no capacity, no drops;
    # engages only when expert weights are unsharded (no ep/tp axis —
    # the kernel has no shard_map partitioning rule yet); other layouts
    # fall back to the einsum path automatically.
    moe_impl: str = "einsum"
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash_attention: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def tiny(**kw) -> "Qwen2MoeConfig":
        return Qwen2MoeConfig(
            vocab_size=256, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, num_experts=4,
            num_experts_per_tok=2, moe_intermediate_size=32,
            shared_expert_intermediate_size=64, **kw)


def init_params(cfg: Qwen2MoeConfig, key: jax.Array) -> Dict[str, Any]:
    D, V = cfg.hidden_size, cfg.vocab_size
    H, Hkv, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    L, E = cfg.num_hidden_layers, cfg.num_experts
    Fm, Fs = cfg.moe_intermediate_size, cfg.shared_expert_intermediate_size
    ks = jax.random.split(key, 16)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) *
                (1.0 / np.sqrt(fan_in))).astype(cfg.dtype)

    layers = {
        "wq": init(ks[0], (L, D, H * Dh), D),
        "wk": init(ks[1], (L, D, Hkv * Dh), D),
        "wv": init(ks[2], (L, D, Hkv * Dh), D),
        "wo": init(ks[3], (L, H * Dh, D), H * Dh),
        "attn_norm": jnp.ones((L, D), cfg.dtype),
        "mlp_norm": jnp.ones((L, D), cfg.dtype),
        # router stays fp32 for stable softmax
        "router": jax.random.normal(ks[4], (L, D, E), jnp.float32) * 0.02,
        "experts": {
            "w_gate": init(ks[5], (L, E, D, Fm), D),
            "w_up": init(ks[6], (L, E, D, Fm), D),
            "w_down": init(ks[7], (L, E, Fm, D), Fm),
        },
        "shared": {
            "w_gate": init(ks[8], (L, D, Fs), D),
            "w_up": init(ks[9], (L, D, Fs), D),
            "w_down": init(ks[10], (L, Fs, D), Fs),
            "gate": init(ks[11], (L, D, 1), D),  # shared-expert gate proj
        },
    }
    return {
        "embed": init(ks[12], (V, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), cfg.dtype),
        "lm_head": init(ks[13], (D, V), D),
    }


def param_specs(cfg: Qwen2MoeConfig) -> Dict[str, Any]:
    """TP shards attention + shared expert like llama; EP shards the E axis
    of routed experts; expert matrices additionally tp-shard their F dim."""
    layers = {
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        "router": P(None, None, None),
        "experts": {
            "w_gate": P(None, "ep", None, "tp"),
            "w_up": P(None, "ep", None, "tp"),
            "w_down": P(None, "ep", "tp", None),
        },
        "shared": {
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
            "gate": P(None, None, None),
        },
    }
    return {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def shard_params(params, cfg: Qwen2MoeConfig, mesh: Mesh):
    specs = param_specs(cfg)

    def put(x, s):
        # drop only the axes absent from this mesh (e.g. no 'ep' axis when
        # ep=1), keeping the rest of the spec intact
        pruned = P(*(n if (n is not None and n in mesh.shape) else None
                     for n in s))
        return jax.device_put(x, NamedSharding(mesh, pruned))

    return jax.tree_util.tree_map(
        put, params, specs, is_leaf=lambda x: isinstance(x, P))


def decoder_layer(lp, h, cfg: Qwen2MoeConfig, ep_axis: Optional[str],
                  use_dropless: bool = False):
    B, T, D = h.shape
    H, Hkv, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))

    x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
    q = (x @ lp["wq"]).reshape(B, T, H, Dh)
    k = (x @ lp["wk"]).reshape(B, T, Hkv, Dh)
    v = (x @ lp["wv"]).reshape(B, T, Hkv, Dh)
    q, k = rope(q, k, positions, cfg.rope_theta, Dh)
    from ..ops.pallas.flash_attention import flash_attention as _fa
    o = _fa(q, k, v, causal=True,
            impl="auto" if cfg.use_flash_attention else "dense")
    h = h + o.reshape(B, T, H * Dh) @ lp["wo"]

    x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
    if use_dropless:
        from ..incubate.moe.functional import moe_ffn_dropless
        routed, aux = moe_ffn_dropless(
            x, lp["router"],
            lp["experts"]["w_gate"], lp["experts"]["w_up"],
            lp["experts"]["w_down"],
            top_k=cfg.num_experts_per_tok)
    else:
        routed, aux = moe_ffn(
            x, lp["router"],
            lp["experts"]["w_gate"], lp["experts"]["w_up"],
            lp["experts"]["w_down"],
            top_k=cfg.num_experts_per_tok,
            capacity_factor=cfg.capacity_factor,
            ep_axis=ep_axis)
    sh = lp["shared"]
    shared = (jax.nn.silu(x @ sh["w_gate"]) * (x @ sh["w_up"])) @ sh["w_down"]
    shared = jax.nn.sigmoid(x @ sh["gate"]) * shared
    return h + routed + shared, aux


def forward(params, tokens, cfg: Qwen2MoeConfig,
            mesh: Optional[Mesh] = None):
    """tokens [B, T] -> (logits [B, T, V], total_aux_loss)."""
    if cfg.moe_impl not in ("einsum", "dropless"):
        raise ValueError(f"moe_impl must be 'einsum' or 'dropless', "
                         f"got {cfg.moe_impl!r}")
    ep_axis = ("ep" if mesh is not None and mesh.shape.get("ep", 1) > 1
               else None)
    # the grouped-GEMM kernel has no GSPMD partitioning rule yet, so
    # dropless only engages on layouts where nothing it touches is
    # sharded: not the expert weights (ep/tp) and not the token
    # activations either (dp — an un-partitionable pallas_call would
    # make XLA replicate the full activation on every dp rank per step)
    use_dropless = (cfg.moe_impl == "dropless" and ep_axis is None
                    and (mesh is None or (mesh.shape.get("tp", 1) == 1
                                          and mesh.shape.get("dp", 1) == 1)))
    h = params["embed"].astype(cfg.dtype)[tokens]

    fn = partial(decoder_layer, cfg=cfg, ep_axis=ep_axis,
                 use_dropless=use_dropless)
    if cfg.remat:
        fn = jax.checkpoint(fn)

    def body(carry, lp):
        h, aux = carry
        h, a = fn(lp, h)
        return (h, aux + a), None

    (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                           params["layers"])
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    return h @ params["lm_head"], aux


def loss_fn(params, batch, cfg: Qwen2MoeConfig, mesh=None):
    tokens, labels = batch["tokens"], batch["labels"]
    logits, aux = forward(params, tokens, cfg, mesh)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return nll.mean() + cfg.router_aux_loss_coef * aux


def make_train_step(cfg: Qwen2MoeConfig, mesh: Mesh, optimizer=None):
    """Jitted SPMD train step; optimizer state inherits param sharding
    (ZeRO-style, like models/llama.py make_train_step)."""
    import optax
    if optimizer is None:
        optimizer = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)

    def init_fn(key):
        params = init_params(cfg, key)
        params = shard_params(params, cfg, mesh)
        opt_state = optimizer.init(params)
        return {"params": params, "opt": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], batch, cfg, mesh)
        updates, opt = optimizer.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt,
                "step": state["step"] + 1}, loss

    return step_fn, init_fn


# ---------------------------------------------------------------------------
# decode: KV cache + generate
# ---------------------------------------------------------------------------
# Reference capability: MoE decode serving (the fused cutlass MoE kernels
# run at inference too). Same cache design as models/llama.py: [L, B, S,
# Hkv, Dh] pytree updated with dynamic_update_slice inside one jitted
# step; the MoE FFN (einsum routing) runs unchanged on T=1 tokens.


def init_kv_cache(cfg: Qwen2MoeConfig, batch_size: int, max_len: int):
    L, Hkv, Dh = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                  cfg.head_dim)
    shape = (L, batch_size, max_len, Hkv, Dh)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def _decode_block(lp, h, positions, cfg: Qwen2MoeConfig, attn_fn):
    """Qwen block math shared by every cached-decode consumer (dense
    cache forward_with_cache AND the serving engine's paged step fns):
    norm -> QKV -> rope -> attn_fn -> o-proj+residual -> norm -> MoE FFN
    (DROP-FREE: capacity cf = E/top_k makes expert capacity == cohort
    size, so no token is ever dropped. Training capacity drops are a
    throughput regularizer; at inference a dropped token silently loses
    its FFN contribution — and the drop pattern depends on cohort size,
    which would make cached decode diverge from a full forward) + shared
    expert + residual. Same signature as models/llama.py _block, so the
    serving step drivers take either."""
    B, T, D = h.shape
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
    q = _mm(x, lp["wq"]).reshape(B, T, H, Dh)
    k = _mm(x, lp["wk"]).reshape(B, T, Hkv, Dh)
    v = _mm(x, lp["wv"]).reshape(B, T, Hkv, Dh)
    q, k = rope(q, k, positions, cfg.rope_theta, Dh)
    o = attn_fn(q, k, v)
    h = h + _mm(o.reshape(B, T, H * Dh), lp["wo"])

    x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
    nodrop_cf = cfg.num_experts / cfg.num_experts_per_tok
    routed, _ = moe_ffn(
        x, lp["router"], _dense_w(lp["experts"]["w_gate"], cfg.dtype),
        _dense_w(lp["experts"]["w_up"], cfg.dtype),
        _dense_w(lp["experts"]["w_down"], cfg.dtype),
        top_k=cfg.num_experts_per_tok,
        capacity_factor=nodrop_cf, ep_axis=None)
    sh = lp["shared"]
    shared = _mm(jax.nn.silu(_mm(x, sh["w_gate"]))
                 * _mm(x, sh["w_up"]), sh["w_down"])
    shared = jax.nn.sigmoid(x @ sh["gate"]) * shared
    return h + routed + shared


def forward_with_cache(params, tokens, cache, pos0, cfg: Qwen2MoeConfig):
    """tokens [B, T] at positions pos0.. -> (last-position logits
    [B, V], updated cache). T = prompt length for prefill (pos0 = 0),
    T = 1 for decode steps."""
    from .llama import _cached_attention
    from ..ops.pallas.flash_attention import flash_attention as _fa
    B, T = tokens.shape
    h = params["embed"].astype(cfg.dtype)[tokens]
    positions = pos0 + jnp.broadcast_to(jnp.arange(T), (B, T))
    is_prefill = isinstance(pos0, int) and pos0 == 0

    def body(h, xs):
        lp, ck, cv = xs
        cell = {}

        def attn_fn(q, k, v):
            ck2 = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                           (0, pos0, 0, 0))
            cv2 = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                           (0, pos0, 0, 0))
            cell["ck"], cell["cv"] = ck2, cv2
            if is_prefill:
                return _fa(q, k, v, causal=True,
                           impl="auto" if cfg.use_flash_attention
                           else "dense")
            return _cached_attention(q, ck2, cv2, pos0, cfg)

        h = _decode_block(lp, h, positions, cfg, attn_fn)
        return h, (cell["ck"], cell["cv"])

    h, (ck_new, cv_new) = lax.scan(
        body, h, (params["layers"], cache["k"], cache["v"]))
    h = rms_norm(h[:, -1], params["final_norm"], cfg.rms_norm_eps)
    logits = _mm(h, params["lm_head"])
    return logits.astype(jnp.float32), {"k": ck_new, "v": cv_new}


def generate(params, prompt, cfg: Qwen2MoeConfig, max_new_tokens: int,
             *, temperature: float = 0.0, top_p: float = 1.0,
             top_k: int = 0, key=None, eos_token_id: Optional[int] = None):
    """Autoregressive MoE decode with a KV cache (same contract as
    models/llama.py generate: returns prompt + continuation). Routing
    is DROP-FREE at decode (see forward_with_cache)."""
    from .llama import _decode_loop
    return _decode_loop(
        lambda p, t, c, pos: forward_with_cache(p, t, c, pos, cfg),
        lambda B, L: init_kv_cache(cfg, B, L),
        params, prompt, max_new_tokens, temperature, top_p, top_k, key,
        eos_token_id)


def make_batch(cfg: Qwen2MoeConfig, batch_size: int, seq_len: int,
               mesh: Mesh, key=None):
    from .llama import make_batch as _llama_make_batch
    return _llama_make_batch(cfg, batch_size, seq_len, mesh, key=key)


# ---------------------------------------------------------------------------
# serving: single-step prefill/decode over a shared page pool
# ---------------------------------------------------------------------------
# Same contracts as models/llama.py's serving fns — the drivers are
# shared; only the block math (here: _decode_block with the drop-free
# MoE FFN) differs. The continuous-batching engine (paddle_tpu/serving/)
# dispatches on the config type.


def abstract_params(cfg: Qwen2MoeConfig):
    """ShapeDtypeStruct pytree of ``init_params`` (tracing-only
    tooling; see models/llama.py abstract_params)."""
    return jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))


def init_serving_pages(cfg: Qwen2MoeConfig, total_pages: int,
                       page_size: int):
    from .llama import init_serving_pages as _impl
    return _impl(cfg, total_pages, page_size)


def serving_prefill(params, tokens, length, table, k_pages, v_pages, cfg,
                    attn_impl: str = "auto"):
    from .llama import serving_prefill as _impl
    return _impl(params, tokens, length, table, k_pages, v_pages, cfg,
                 attn_impl=attn_impl, _block_fn=_decode_block)


def serving_prefill_chunk(params, tokens, length, table, k_pages, v_pages,
                          cfg, prefix_pages: int, attn_impl: str = "auto"):
    from .llama import serving_prefill_chunk as _impl
    return _impl(params, tokens, length, table, k_pages, v_pages, cfg,
                 prefix_pages, attn_impl=attn_impl,
                 _block_fn=_decode_block)


def serving_decode_step(params, tok, lengths, tables, k_pages, v_pages,
                        cfg, attn_impl: str = "auto"):
    from .llama import serving_decode_step as _impl
    return _impl(params, tok, lengths, tables, k_pages, v_pages, cfg,
                 attn_impl=attn_impl, _block_fn=_decode_block)


def serving_decode_block(params, tok, lengths, tables, k_pages, v_pages,
                         cfg, num_steps: int, attn_impl: str = "auto"):
    from .llama import serving_decode_block as _impl
    return _impl(params, tok, lengths, tables, k_pages, v_pages, cfg,
                 num_steps, attn_impl=attn_impl, _block_fn=_decode_block)


def serving_tick(params, tokens, meta, k_pages, v_pages, cfg,
                 tq: int = 1, decode_tail: int = 0, spec_k: int = 0,
                 attn_impl: str = "auto"):
    from .llama import serving_tick as _impl
    return _impl(params, tokens, meta, k_pages, v_pages, cfg, tq=tq,
                 decode_tail=decode_tail, spec_k=spec_k,
                 attn_impl=attn_impl, _block_fn=_decode_block)


def serving_tick_block(params, tok, lengths, tables, k_pages, v_pages,
                       cfg, num_steps: int, attn_impl: str = "auto",
                       sampling=None):
    from .llama import serving_tick_block as _impl
    return _impl(params, tok, lengths, tables, k_pages, v_pages, cfg,
                 num_steps, attn_impl=attn_impl, _block_fn=_decode_block,
                 sampling=sampling)

"""Shared helpers for the vision model-zoo factories."""
from __future__ import annotations


def check_no_pretrained(pretrained: bool):
    """Single place for the no-weight-hub policy (zero-egress build)."""
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need a download hub (zero-egress build); "
            "load converted weights with model.set_state_dict instead")


def zoo_factory(cls, name: str, **fixed):
    """Factory with a real __name__ (closure-based 'make' degrades
    tracebacks and repr)."""
    def make(pretrained: bool = False, **kwargs):
        check_no_pretrained(pretrained)
        return cls(**{**fixed, **kwargs})
    make.__name__ = make.__qualname__ = name
    make.__doc__ = f"Build {cls.__name__} ({fixed or 'defaults'})."
    return make

"""paddle_tpu.models — reference model families, TPU-first.

The flagship pretrain path (llama.py) is functional JAX: params are a pytree,
the train step is one jitted SPMD program over the hybrid mesh. Eager
``nn.Layer`` wrappers exist for the vision models (lenet.py, resnet.py),
mirroring the reference's python/paddle/vision/models/.
"""
from . import llama
from . import qwen2_moe
from .llama import LlamaConfig
from .qwen2_moe import Qwen2MoeConfig
from .lenet import LeNet

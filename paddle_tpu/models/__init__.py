"""paddle_tpu.models — reference model families, TPU-first.

The flagship pretrain path (llama.py) is functional JAX: params are a pytree,
the train step is one jitted SPMD program over the hybrid mesh. Eager
``nn.Layer`` wrappers exist for the vision models (lenet.py, resnet.py),
mirroring the reference's python/paddle/vision/models/.
"""
from . import llama
from . import qwen2_moe
from .llama import LlamaConfig
from .qwen2_moe import Qwen2MoeConfig
from .lenet import LeNet
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .alexnet import AlexNet, alexnet
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3Small,
                        MobileNetV3Large, mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_small, mobilenet_v3_large)
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .shufflenetv2 import (ShuffleNetV2, shufflenet_v2_x0_25,
                           shufflenet_v2_x0_33, shufflenet_v2_x0_5,
                           shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                           shufflenet_v2_x2_0, shufflenet_v2_swish)
from .googlenet import GoogLeNet, googlenet
from .inceptionv3 import InceptionV3, inception_v3
from .ernie import (ErnieConfig, ErnieModel, ErnieForSequenceClassification,
                    ErnieForPretraining)

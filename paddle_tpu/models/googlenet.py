"""GoogLeNet (Inception v1).

Reference: python/paddle/vision/models/googlenet.py (Inception block
with 4 branches; two aux classifier heads active in train mode; returns
(main, aux1, aux2) like the reference).
"""
from __future__ import annotations

from .. import nn
from ._zoo import check_no_pretrained
from ..ops.manipulation import concat

__all__ = ["GoogLeNet", "googlenet"]


class Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_c, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(in_c, proj, 1), nn.ReLU())

    def forward(self, x):
        # registered concat: keeps the autograd tape through the block
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = nn.Conv2D(in_c, 128, 1)
        self.relu = nn.ReLU()
        self.fc1 = nn.Linear(128 * 4 * 4, 1024)
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.relu(self.conv(self.pool(x)))
        x = self.relu(self.fc1(x.flatten(1)))
        return self.fc2(self.drop(x))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = Inception(832, 384, 192, 384, 48, 128, 128)
        self.aux1 = _AuxHead(512, num_classes if num_classes > 0 else 1000)
        self.aux2 = _AuxHead(528, num_classes if num_classes > 0 else 1000)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc3b(self.inc3a(self.stem(x)))
        x = self.inc4a(self.pool3(x))
        aux1 = self.aux1(x) if self.training else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if self.training else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    check_no_pretrained(pretrained)
    return GoogLeNet(**kwargs)

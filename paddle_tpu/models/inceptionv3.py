"""Inception v3.

Reference: python/paddle/vision/models/inceptionv3.py (InceptionA-E
blocks with the factorized 7x1/1x7 and 3x1/1x3 convs; 299x299 input).
"""
from __future__ import annotations

from .. import nn
from ._zoo import check_no_pretrained
from ..ops.manipulation import concat

__all__ = ["InceptionV3", "inception_v3"]


def _cat(*ts):
    # registered concat: keeps the autograd tape through the block
    return concat(list(ts), axis=1)


class BasicConv2D(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                      bias_attr=False),
            nn.BatchNorm2D(out_c), nn.ReLU())


class InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = BasicConv2D(in_c, 64, 1)
        self.b5 = nn.Sequential(BasicConv2D(in_c, 48, 1),
                                BasicConv2D(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(BasicConv2D(in_c, 64, 1),
                                BasicConv2D(64, 96, 3, padding=1),
                                BasicConv2D(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                BasicConv2D(in_c, pool_features, 1))

    def forward(self, x):
        return _cat(self.b1(x), self.b5(x), self.b3(x), self.bp(x))


class InceptionB(nn.Layer):
    """grid reduction 35->17"""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = BasicConv2D(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(BasicConv2D(in_c, 64, 1),
                                 BasicConv2D(64, 96, 3, padding=1),
                                 BasicConv2D(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat(self.b3(x), self.b3d(x), self.pool(x))


class InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = BasicConv2D(in_c, 192, 1)
        self.b7 = nn.Sequential(
            BasicConv2D(in_c, c7, 1),
            BasicConv2D(c7, c7, (1, 7), padding=(0, 3)),
            BasicConv2D(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            BasicConv2D(in_c, c7, 1),
            BasicConv2D(c7, c7, (7, 1), padding=(3, 0)),
            BasicConv2D(c7, c7, (1, 7), padding=(0, 3)),
            BasicConv2D(c7, c7, (7, 1), padding=(3, 0)),
            BasicConv2D(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                BasicConv2D(in_c, 192, 1))

    def forward(self, x):
        return _cat(self.b1(x), self.b7(x), self.b7d(x), self.bp(x))


class InceptionD(nn.Layer):
    """grid reduction 17->8"""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(BasicConv2D(in_c, 192, 1),
                                BasicConv2D(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            BasicConv2D(in_c, 192, 1),
            BasicConv2D(192, 192, (1, 7), padding=(0, 3)),
            BasicConv2D(192, 192, (7, 1), padding=(3, 0)),
            BasicConv2D(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat(self.b3(x), self.b7(x), self.pool(x))


class InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = BasicConv2D(in_c, 320, 1)
        self.b3_1 = BasicConv2D(in_c, 384, 1)
        self.b3_2a = BasicConv2D(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = BasicConv2D(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = nn.Sequential(BasicConv2D(in_c, 448, 1),
                                  BasicConv2D(448, 384, 3, padding=1))
        self.bd_2a = BasicConv2D(384, 384, (1, 3), padding=(0, 1))
        self.bd_2b = BasicConv2D(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                BasicConv2D(in_c, 192, 1))

    def forward(self, x):
        b3 = self.b3_1(x)
        bd = self.bd_1(x)
        return _cat(self.b1(x), self.b3_2a(b3), self.b3_2b(b3),
                    self.bd_2a(bd), self.bd_2b(bd), self.bp(x))


class InceptionV3(nn.Layer):
    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            BasicConv2D(3, 32, 3, stride=2),
            BasicConv2D(32, 32, 3),
            BasicConv2D(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            BasicConv2D(64, 80, 1),
            BasicConv2D(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.drop = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    check_no_pretrained(pretrained)
    return InceptionV3(**kwargs)

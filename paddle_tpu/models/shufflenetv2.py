"""ShuffleNetV2 family.

Reference: python/paddle/vision/models/shufflenetv2.py (channel-shuffle
inverted residual units; x0_25..x2_0 + swish variant).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


def channel_shuffle(x, groups: int):
    data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    n, c, h, w = data.shape
    data = data.reshape(n, groups, c // groups, h, w)
    data = jnp.swapaxes(data, 1, 2).reshape(n, c, h, w)
    return Tensor(data)


class _ConvBNAct(nn.Sequential):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act=nn.ReLU):
        layers = [nn.Conv2D(in_c, out_c, k, stride=stride,
                            padding=(k - 1) // 2, groups=groups,
                            bias_attr=False),
                  nn.BatchNorm2D(out_c)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class ShuffleUnit(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, channels, act):
        super().__init__()
        c = channels // 2
        self.branch = nn.Sequential(
            _ConvBNAct(c, c, 1, act=act),
            _ConvBNAct(c, c, 3, groups=c, act=None),
            _ConvBNAct(c, c, 1, act=act))
        self._c = c

    def forward(self, x):
        data = x.data
        x1, x2 = data[:, :self._c], data[:, self._c:]
        out = jnp.concatenate([x1, self.branch(Tensor(x2)).data], axis=1)
        return channel_shuffle(Tensor(out), 2)


class ShuffleDownUnit(nn.Layer):
    """stride-2 unit: both branches transform, spatial halved."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        c = out_c // 2
        self.branch1 = nn.Sequential(
            _ConvBNAct(in_c, in_c, 3, stride=2, groups=in_c, act=None),
            _ConvBNAct(in_c, c, 1, act=act))
        self.branch2 = nn.Sequential(
            _ConvBNAct(in_c, c, 1, act=act),
            _ConvBNAct(c, c, 3, stride=2, groups=c, act=None),
            _ConvBNAct(c, c, 1, act=act))

    def forward(self, x):
        out = jnp.concatenate(
            [self.branch1(x).data, self.branch2(x).data], axis=1)
        return channel_shuffle(Tensor(out), 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        outs = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _ConvBNAct(3, outs[0], 3, stride=2, act=act_layer)
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = outs[0]
        for si, reps in enumerate(_STAGE_REPEATS):
            out_c = outs[si + 1]
            stages.append(ShuffleDownUnit(in_c, out_c, act_layer))
            stages += [ShuffleUnit(out_c, act_layer) for _ in range(reps - 1)]
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _ConvBNAct(in_c, outs[-1], 1, act=act_layer)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.pool1(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _factory(scale, act="relu"):
    def make(pretrained=False, **kwargs):
        if pretrained:
            raise NotImplementedError("no pretrained weight hub in this build")
        return ShuffleNetV2(scale=scale, act=act, **kwargs)
    return make


shufflenet_v2_x0_25 = _factory(0.25)
shufflenet_v2_x0_33 = _factory(0.33)
shufflenet_v2_x0_5 = _factory(0.5)
shufflenet_v2_x1_0 = _factory(1.0)
shufflenet_v2_x1_5 = _factory(1.5)
shufflenet_v2_x2_0 = _factory(2.0)
shufflenet_v2_swish = _factory(1.0, act="swish")

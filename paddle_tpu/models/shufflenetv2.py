"""ShuffleNetV2 family.

Reference: python/paddle/vision/models/shufflenetv2.py (channel-shuffle
inverted residual units; x0_25..x2_0 + swish variant).
"""
from __future__ import annotations

from .. import nn
from ..ops.manipulation import concat
from .mobilenet import ConvBNReLU

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


def channel_shuffle(x, groups: int):
    """Tracked reshape/transpose ops only — the tape must flow through."""
    n, c, h, w = x.shape
    x = x.reshape([n, groups, c // groups, h, w])
    return x.transpose([0, 2, 1, 3, 4]).reshape([n, c, h, w])


class ShuffleUnit(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, channels, act):
        super().__init__()
        c = channels // 2
        self.branch = nn.Sequential(
            ConvBNReLU(c, c, 1, act=act),
            ConvBNReLU(c, c, 3, groups=c, act=None),
            ConvBNReLU(c, c, 1, act=act))
        self._c = c

    def forward(self, x):
        x1, x2 = x[:, :self._c], x[:, self._c:]
        return channel_shuffle(concat([x1, self.branch(x2)], axis=1), 2)


class ShuffleDownUnit(nn.Layer):
    """stride-2 unit: both branches transform, spatial halved."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        c = out_c // 2
        self.branch1 = nn.Sequential(
            ConvBNReLU(in_c, in_c, 3, stride=2, groups=in_c, act=None),
            ConvBNReLU(in_c, c, 1, act=act))
        self.branch2 = nn.Sequential(
            ConvBNReLU(in_c, c, 1, act=act),
            ConvBNReLU(c, c, 3, stride=2, groups=c, act=None),
            ConvBNReLU(c, c, 1, act=act))

    def forward(self, x):
        return channel_shuffle(
            concat([self.branch1(x), self.branch2(x)], axis=1), 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, act: str = "relu",
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        outs = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNReLU(3, outs[0], 3, stride=2, act=act_layer)
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = outs[0]
        for si, reps in enumerate(_STAGE_REPEATS):
            out_c = outs[si + 1]
            stages.append(ShuffleDownUnit(in_c, out_c, act_layer))
            stages += [ShuffleUnit(out_c, act_layer) for _ in range(reps - 1)]
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = ConvBNReLU(in_c, outs[-1], 1, act=act_layer)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.pool1(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


from ._zoo import zoo_factory

shufflenet_v2_x0_25 = zoo_factory(ShuffleNetV2, "shufflenet_v2_x0_25", scale=0.25)
shufflenet_v2_x0_33 = zoo_factory(ShuffleNetV2, "shufflenet_v2_x0_33", scale=0.33)
shufflenet_v2_x0_5 = zoo_factory(ShuffleNetV2, "shufflenet_v2_x0_5", scale=0.5)
shufflenet_v2_x1_0 = zoo_factory(ShuffleNetV2, "shufflenet_v2_x1_0", scale=1.0)
shufflenet_v2_x1_5 = zoo_factory(ShuffleNetV2, "shufflenet_v2_x1_5", scale=1.5)
shufflenet_v2_x2_0 = zoo_factory(ShuffleNetV2, "shufflenet_v2_x2_0", scale=2.0)
shufflenet_v2_swish = zoo_factory(ShuffleNetV2, "shufflenet_v2_swish", scale=1.0, act="swish")

"""MobileNet V1 / V2 / V3.

Reference: python/paddle/vision/models/{mobilenetv1,mobilenetv2,
mobilenetv3}.py — same block structure (depthwise-separable / inverted
residual / V3 SE + hard activations) and constructor surface
(scale, num_classes, with_pool).

TPU note: depthwise convs (groups == channels) lower to XLA
feature-group convolutions; at scale they are HBM-bound, which is fine
— they carry <5% of the FLOPs.
"""
from __future__ import annotations

from .. import nn

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
           "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1,
                 act=nn.ReLU):
        padding = (kernel - 1) // 2
        layers = [nn.Conv2D(in_c, out_c, kernel, stride=stride,
                            padding=padding, groups=groups, bias_attr=False),
                  nn.BatchNorm2D(out_c)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


# ------------------------------------------------------------------ V1
class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.depthwise = ConvBNReLU(in_c, in_c, 3, stride, groups=in_c)
        self.pointwise = ConvBNReLU(in_c, out_c, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return int(ch * scale)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        blocks = [ConvBNReLU(3, c(32), 3, stride=2)]
        blocks += [DepthwiseSeparable(c(i), c(o), s) for i, o, s in cfg]
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


# ------------------------------------------------------------------ V2
class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(in_c, hidden, 1, act=nn.ReLU6))
        layers += [
            ConvBNReLU(hidden, hidden, 3, stride, groups=hidden,
                       act=nn.ReLU6),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        blocks = [ConvBNReLU(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, ch, n, s in cfg:
            out_c = _make_divisible(ch * scale)
            for i in range(n):
                blocks.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        blocks.append(ConvBNReLU(in_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


# ------------------------------------------------------------------ V3
class SqueezeExcitation(nn.Layer):
    def __init__(self, channels, squeeze_factor=4):
        super().__init__()
        sq = _make_divisible(channels // squeeze_factor)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(channels, sq, 1)
        self.fc2 = nn.Conv2D(sq, channels, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class V3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers.append(ConvBNReLU(in_c, exp_c, 1, act=act))
        layers.append(ConvBNReLU(exp_c, exp_c, kernel, stride,
                                 groups=exp_c, act=act))
        if use_se:
            layers.append(SqueezeExcitation(exp_c))
        layers += [nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [
    # k, exp, out, se, act, stride
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]
_V3_LARGE = [
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        blocks = [ConvBNReLU(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(V3Block(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        last_c = _make_divisible(last_exp * scale)
        blocks.append(ConvBNReLU(in_c, last_c, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            head_c = 1024 if cfg is _V3_SMALL else 1280
            self.classifier = nn.Sequential(
                nn.Linear(last_c, head_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(head_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


from ._zoo import zoo_factory

mobilenet_v1 = zoo_factory(MobileNetV1, "mobilenet_v1")
mobilenet_v2 = zoo_factory(MobileNetV2, "mobilenet_v2")
mobilenet_v3_small = zoo_factory(MobileNetV3Small, "mobilenet_v3_small")
mobilenet_v3_large = zoo_factory(MobileNetV3Large, "mobilenet_v3_large")

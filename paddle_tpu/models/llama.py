"""Llama-family decoder, functional and TPU-first.

This is the flagship pretrain path: the capability target is the reference's
hybrid-parallel Llama training stack (fleet TP layers mp_layers.py, pipeline
schedules pipeline_parallel.py, sharding optimizer, sequence-parallel utils —
see SURVEY.md §2.8/§3.4), redesigned as ONE jitted SPMD program:

  - params are a plain pytree; per-layer weights are stacked on a leading
    layer axis and consumed by ``lax.scan`` (fast compiles, XLA-friendly);
  - TP  = GSPMD sharding annotations on weights (column/row parallel exactly
    where fleet's ColumnParallelLinear/RowParallelLinear shard);
  - SP  = sequence-sharded residual stream between blocks over the tp axis
    (megatron sequence parallel, sequence_parallel_utils.py:427);
  - PP  = microbatch pipeline via parallel.pipeline_spmd (collective-permute
    ring instead of NCCL isend/irecv);
  - DP/ZeRO = batch sharded over dp; optimizer state sharded like params.

XLA inserts every collective (all-gather / reduce-scatter / ppermute) from
the sharding annotations — there is no hand-written communication here.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.pipeline_spmd import pipeline_spmd, microbatch


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # parallelism
    pp_stages: int = 1
    num_microbatches: int = 1
    # "gpipe": autodiff through the SPMD pipeline (pipeline_spmd) — all
    # forwards then all backwards, O(M) live microbatch activations.
    # "1f1b": explicit fused fwd+bwd LOCKSTEP schedule (pipeline_1f1b)
    # — O(S) live activations, matching pipeline_parallel.py:565, but
    # every tick runs every slot (fill/drain = masked work).
    # "1f1b_async": rank-asymmetric 1F1B (pipeline_async) — shard_map
    # body branching on stage index, reference per-rank bubble
    # 1-(S-1)/(VM+S-1); composes dp (row-sharded microbatches, grad
    # psum folded into the f32 accumulation carry) and tp (manual
    # megatron f/g collectives in the stage body, vocab-parallel CE
    # in the head) since r19.
    # "zb": ZB-H1-style W-deferral on top of 1f1b_async
    # (pipeline_zero_bubble.py counterpart); V=1, W consumes
    # ring-saved residuals (~4.5 work units vs the fused 4).
    pp_schedule: str = "gpipe"
    # interleaved VPP: chunks per device under the 1f1b schedule
    # (pipeline_parallel.py:1372 round-robin model partition)
    vpp_chunks: int = 1
    remat: bool = True
    # kernels: True/"auto" (pallas when shapes allow), "pallas" (strict:
    # error instead of silently falling back to dense — the bench runs
    # this), False/"dense"
    use_flash_attention: Any = True
    # fused rmsnorm/rope pallas kernels between the GEMMs
    # (ops/pallas/fused_norm_rope; counterpart of the reference's
    # fused_rms_norm/fused_rope fusion kernels). "auto": on when running
    # on TPU. Under a tp/cp-sharded residual stream the kernels run per
    # shard via the *_sharded shard_map entries (norm/rope are token- and
    # head-local, like the reference's per-rank fused kernels under TP);
    # in the pp>1 stage loop — where stages run under vmap, which does
    # not compose with shard_map — the jnp formulation runs instead.
    # True/"pallas": always (interpret mode off-TPU). False: never.
    use_fused_norm_rope: Any = "auto"
    # context parallelism: "none" | "ring" | "ulysses" | "zigzag" —
    # shards the sequence dim over the mesh cp axis
    # (parallel/context_parallel.py). "zigzag" is the causal-balanced
    # ring: tokens are laid out so every rank owns one head + one tail
    # cell and each ring hop carries equal unmasked work.
    context_parallel: str = "none"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, rope_theta=500000.0, **kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """Test/dryrun config."""
        return LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128, **kw)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Init a params pytree; per-layer tensors stacked on a leading L axis."""
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    H, Hkv, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    L = cfg.num_hidden_layers
    ks = jax.random.split(key, 10)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) *
                (1.0 / np.sqrt(fan_in))).astype(cfg.dtype)

    layers = {
        "wq": init(ks[0], (L, D, H * Dh), D),
        "wk": init(ks[1], (L, D, Hkv * Dh), D),
        "wv": init(ks[2], (L, D, Hkv * Dh), D),
        "wo": init(ks[3], (L, H * Dh, D), H * Dh),
        "w_gate": init(ks[4], (L, D, F), D),
        "w_up": init(ks[5], (L, D, F), D),
        "w_down": init(ks[6], (L, F, D), F),
        "attn_norm": jnp.ones((L, D), cfg.dtype),
        "mlp_norm": jnp.ones((L, D), cfg.dtype),
    }
    return {
        "embed": init(ks[7], (V, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), cfg.dtype),
        "lm_head": init(ks[8], (D, V), D),
    }


def abstract_params(cfg: LlamaConfig):
    """ShapeDtypeStruct pytree of ``init_params`` output without
    computing (or allocating) anything — what tracing-only tooling
    (analysis/serving_graphs.py graph lint, cost models) feeds to
    ``jax.make_jaxpr`` so a lint run costs milliseconds, not an init."""
    return jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))


def param_specs(cfg: LlamaConfig) -> Dict[str, Any]:
    """PartitionSpecs: where fleet's TP layers shard, we annotate.

    Column-parallel (out-dim on tp): wq/wk/wv, w_gate/w_up — fleet's
    ColumnParallelLinear (mp_layers.py). Row-parallel (in-dim on tp):
    wo, w_down — RowParallelLinear. Vocab-parallel embedding shards the
    vocab dim; lm_head is column-parallel over vocab (ParallelCrossEntropy
    consumes vocab-sharded logits). Leading axis of layer weights is the
    layer/stage axis: sharded over pp when pipelining.
    """
    pp = "pp" if cfg.pp_stages > 1 else None
    layers = {
        "wq": P(pp, None, "tp"),
        "wk": P(pp, None, "tp"),
        "wv": P(pp, None, "tp"),
        "wo": P(pp, "tp", None),
        "w_gate": P(pp, None, "tp"),
        "w_up": P(pp, None, "tp"),
        "w_down": P(pp, "tp", None),
        "attn_norm": P(pp, None),
        "mlp_norm": P(pp, None),
    }
    return {
        "embed": P("tp", None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def shard_params(params, cfg: LlamaConfig, mesh: Mesh):
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# model math
# ---------------------------------------------------------------------------

def _mm(x, w):
    """``x @ w`` for a dense weight or an ``Int8Weight`` (the weight-only
    int8 decode path, quantization/decode.py): the per-channel dequant is
    fused into the matmul (ops/fused/int8_matmul). Dense weights — the
    training path — take the plain-``@`` branch, so nothing changes for
    them."""
    dm = getattr(w, "dequant_matmul", None)
    return x @ w if dm is None else dm(x)


def rms_norm(x, weight, eps):
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * weight.astype(jnp.float32)).astype(dt)


def rope(q, k, positions, theta, head_dim):
    """Rotary embedding applied to [B, T, H, Dh] q/k."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,half]
    cos, sin = jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                              axis=-1)
        return out.astype(x.dtype)

    return rot(q), rot(k)


def attention(q, k, v, cfg: LlamaConfig):
    """Causal GQA attention, dense path (single implementation lives in
    ops/pallas/flash_attention; this forces impl='dense')."""
    from ..ops.pallas.flash_attention import flash_attention as _fa
    return _fa(q, k, v, causal=True, impl="dense")


def _fused_nr_on(cfg: LlamaConfig, mesh) -> bool:
    """Whether the fused pallas rmsnorm/rope kernels replace the jnp
    formulations in the layer body (see LlamaConfig.use_fused_norm_rope)."""
    v = getattr(cfg, "use_fused_norm_rope", "auto")
    if v in (False, "off", "dense"):
        return False
    if v in (True, "pallas"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _spec_divides(mesh, spec, shape) -> bool:
    """Whether every sharded dim of ``shape`` divides its mesh axis size
    (shard_map requires even splits; GSPMD would pad, shard_map raises)."""
    for dim, ax in zip(shape, tuple(spec)):
        if ax is None:
            continue
        axes = ax if isinstance(ax, (tuple, list)) else (ax,)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        if dim % size:
            return False
    return True


def _tp_heads_shardable(cfg: LlamaConfig, mesh) -> bool:
    """Whether q/k/v head dims can shard over tp: the GQA group structure
    survives a head split iff BOTH head counts divide the tp degree."""
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    return (tp > 1 and cfg.num_attention_heads % tp == 0
            and cfg.num_key_value_heads % tp == 0)


def _norm_fn(cfg: LlamaConfig, mesh, fused: bool, h_spec=None):
    """The rms_norm callable: fused pallas kernel (per-shard via shard_map
    when ``h_spec`` gives the stream's PartitionSpec) or the jnp path."""
    if fused and h_spec is not None:
        from ..ops.pallas.fused_norm_rope import fused_rms_norm_sharded
        return lambda x, w: fused_rms_norm_sharded(x, w, mesh, h_spec,
                                                   cfg.rms_norm_eps)
    if fused:
        from ..ops.pallas.fused_norm_rope import fused_rms_norm
        return lambda x, w: fused_rms_norm(x, w, cfg.rms_norm_eps)
    return lambda x, w: rms_norm(x, w, cfg.rms_norm_eps)


def _fused_shard_specs(cfg: LlamaConfig, mesh, sp_spec):
    """PartitionSpecs for running the fused norm/rope kernels per shard
    when the residual stream is sequence-sharded (megatron SP over tp, or
    context parallel over cp).

    Returns ``(h_spec, rope_specs)`` where ``rope_specs`` is
    ``(q_spec, k_spec, pos_spec)`` or None (rope then runs the jnp path —
    e.g. GQA head counts not divisible by the tp degree). Returns None
    outright when there is no mesh context to shard_map over.
    """
    if mesh is None or sp_spec is None:
        return None
    h_spec = sp_spec.spec if hasattr(sp_spec, "spec") else sp_spec
    dp_ax, seq_ax = h_spec[0], h_spec[1]
    tp = mesh.shape.get("tp", 1)
    # q/k leave the column-parallel QKV matmul head-sharded over tp
    head_ax = "tp" if _tp_heads_shardable(cfg, mesh) else None
    if seq_ax == "tp":
        # megatron SP: the matmul all-gathers the seq dim; heads carry tp
        if head_ax is None:
            rope_specs = None
        else:
            qk = P(dp_ax, None, "tp", None)
            rope_specs = (qk, qk, P(dp_ax, None))
    elif seq_ax is not None:
        # context parallel: seq stays sharded through rope (positions are
        # per-token, so any layout — zigzag included — shards with it)
        if tp > 1 and head_ax is None:
            rope_specs = None  # heads carry tp but do not divide it
        else:
            qk = P(dp_ax, seq_ax, head_ax, None)
            rope_specs = (qk, qk, P(dp_ax, seq_ax))
    else:
        rope_specs = None
    return h_spec, rope_specs


def _block(lp, h, positions, cfg: LlamaConfig, attn_fn, sp_spec=None,
           fused_nr=False, mesh=None):
    """The transformer block math shared by the training path
    (decoder_layer) and the KV-cache decode path (forward_with_cache):
    rms_norm -> QKV -> rope -> ``attn_fn(q, k, v)`` -> o-proj+residual ->
    rms_norm -> SwiGLU+residual. One source of truth — attention strategy
    is the only thing the two paths vary.

    With ``fused_nr`` and a sequence-sharded residual stream (sp_spec),
    the fused pallas kernels run per shard via the *_sharded shard_map
    entries (fused_norm_rope.py) — norm and rope are token/head-local, so
    the sharded stream no longer forces the slow jnp path."""
    B, T, D = h.shape
    H, Hkv, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    sharded = None
    if fused_nr and sp_spec is not None:
        sharded = _fused_shard_specs(cfg, mesh, sp_spec)
        if sharded is not None and not _spec_divides(mesh, sharded[0],
                                                    h.shape):
            sharded = None  # uneven split: shard_map would raise
        if sharded is None:
            fused_nr = False  # sharded stream, no mesh context: jnp
        elif sharded[1] is not None and not _spec_divides(
                mesh, sharded[1][0], (B, T, H, Dh)):
            sharded = (sharded[0], None)  # rope alone falls back to jnp
    norm = _norm_fn(cfg, mesh, fused_nr, sharded[0] if sharded else None)
    if fused_nr and sharded is not None and sharded[1] is not None:
        from ..ops.pallas.fused_norm_rope import fused_rope_sharded
        q_spec, k_spec, pos_spec = sharded[1]
        rope_fn = lambda q, k: fused_rope_sharded(
            q, k, positions, mesh, q_spec, k_spec, pos_spec, cfg.rope_theta)
    elif fused_nr and sharded is None:
        from ..ops.pallas.fused_norm_rope import fused_rope
        rope_fn = lambda q, k: fused_rope(q, k, positions, cfg.rope_theta)
    else:
        rope_fn = lambda q, k: rope(q, k, positions, cfg.rope_theta, Dh)
    x = norm(h, lp["attn_norm"])
    q = _mm(x, lp["wq"]).reshape(B, T, H, Dh)
    k = _mm(x, lp["wk"]).reshape(B, T, Hkv, Dh)
    v = _mm(x, lp["wv"]).reshape(B, T, Hkv, Dh)
    q, k = rope_fn(q, k)
    o = attn_fn(q, k, v)
    # tag for remat policies: lets a save_only_these_names policy keep the
    # kernel output so backward recompute skips the flash forward (the
    # default bench path uses plain per-layer remat, measured faster)
    o = checkpoint_name(o, "attn_out")
    h = h + _mm(o.reshape(B, T, H * Dh), lp["wo"])
    if sp_spec is not None:
        # sequence-parallel residual stream: reduce-scatter the row-parallel
        # output over tp along the seq dim (sequence_parallel_utils.py:427)
        h = lax.with_sharding_constraint(h, sp_spec)

    x = norm(h, lp["mlp_norm"])
    h = h + _mm(jax.nn.silu(_mm(x, lp["w_gate"])) * _mm(x, lp["w_up"]),
                lp["w_down"])
    if sp_spec is not None:
        h = lax.with_sharding_constraint(h, sp_spec)
    return h


def _train_attn_fn(cfg: LlamaConfig, mesh):
    """Attention callable for the training path: context-parallel when a
    cp axis is live, otherwise the flash kernel per cfg — run per tp
    shard over the head dim when tp shards the stream (attention is
    head-local; GQA grouping survives because Hkv % tp == 0), so the
    opaque pallas call never makes GSPMD all-gather the activations."""
    cp_on = (cfg.context_parallel != "none" and mesh is not None
             and mesh.shape.get("cp", 1) > 1)
    if cp_on:
        from ..parallel.context_parallel import context_parallel_attention
        return lambda q, k, v: context_parallel_attention(
            q, k, v, mesh, impl=cfg.context_parallel)
    from ..ops.pallas.flash_attention import flash_attention as _fa
    fa = cfg.use_flash_attention
    impl = fa if isinstance(fa, str) else ("auto" if fa else "dense")
    if _tp_heads_shardable(cfg, mesh):
        from .._compat import shard_map
        dp_ax = "dp" if "dp" in mesh.shape else None
        spec = P(dp_ax, None, "tp", None)
        body = lambda ql, kl, vl: _fa(ql, kl, vl, causal=True, impl=impl)

        def attn(q, k, v):
            if not _spec_divides(mesh, spec, q.shape):
                # uneven batch split: plain GSPMD call instead of a
                # shard_map trace error
                return _fa(q, k, v, causal=True, impl=impl)
            return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)
        return attn
    return lambda q, k, v: _fa(q, k, v, causal=True, impl=impl)


def decoder_layer(lp, h, cfg: LlamaConfig, sp_spec=None, mesh=None,
                  positions=None):
    """One transformer block on [B, T, D]. ``lp`` holds this layer's
    (unstacked) weights. ``positions``: global token positions [B, T]
    (defaults to arange — zigzag CP passes its permuted layout)."""
    B, T, _ = h.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    return _block(lp, h, positions, cfg, _train_attn_fn(cfg, mesh),
                  sp_spec=sp_spec, fused_nr=_fused_nr_on(cfg, mesh),
                  mesh=mesh)


def _scan_layers(layer_params, h, cfg: LlamaConfig, sp_spec=None, remat=False,
                 mesh=None, positions=None):
    fn = partial(decoder_layer, cfg=cfg, sp_spec=sp_spec, mesh=mesh,
                 positions=positions)
    if remat:
        # measured on-chip: plain full per-layer remat beats
        # save_only_these_names("attn_out") by ~2% step time at bench
        # shapes (the saved flash recompute is outweighed by HBM pressure)
        fn = jax.checkpoint(fn)

    def body(carry, lp):
        return fn(lp, carry), None

    h, _ = lax.scan(body, h, layer_params)
    return h


def _zigzag_on(cfg: LlamaConfig, mesh) -> bool:
    return (cfg.context_parallel == "zigzag" and mesh is not None
            and mesh.shape.get("cp", 1) > 1)


def forward(params, tokens, cfg: LlamaConfig, mesh: Optional[Mesh] = None):
    """tokens [B, T] -> logits [B, T, V]. Single pipeline stage (pp=1).

    Under zigzag CP the sequence is internally re-laid-out (one head +
    one tail cell per cp rank, parallel/context_parallel.py
    zigzag_global_perm) — logits come back in that order; loss_fn
    permutes the labels identically, so training is order-consistent.
    """
    sp_spec = None
    positions = None
    if mesh is not None and mesh.shape.get("cp", 1) > 1:
        # context parallel: residual stream sequence-sharded over cp
        sp_spec = NamedSharding(mesh, P("dp", "cp", None))
        if _zigzag_on(cfg, mesh):
            from ..parallel.context_parallel import zigzag_global_perm
            perm = zigzag_global_perm(tokens.shape[1], mesh.shape["cp"])
            tokens = tokens[:, perm]
            positions = jnp.broadcast_to(jnp.asarray(perm), tokens.shape)
    elif mesh is not None and mesh.shape.get("tp", 1) > 1:
        sp_spec = NamedSharding(mesh, P("dp", "tp", None))
    h = params["embed"].astype(cfg.dtype)[tokens]
    if sp_spec is not None:
        h = lax.with_sharding_constraint(h, sp_spec)
    h = _scan_layers(params["layers"], h, cfg, sp_spec, remat=cfg.remat,
                     mesh=mesh, positions=positions)
    fin_spec = sp_spec.spec if sp_spec is not None else None
    if fin_spec is not None and not _spec_divides(mesh, fin_spec, h.shape):
        fin_spec = None  # uneven split: run the jnp norm instead
        fused_fin = False
    else:
        fused_fin = _fused_nr_on(cfg, mesh)
    h = _norm_fn(cfg, mesh, fused_fin, fin_spec)(h, params["final_norm"])
    return _mm(h, params["lm_head"])


def _split_stages(layer_params, cfg: LlamaConfig):
    """[L, ...] stacked layers -> [S, L/S, ...] (stage axis leading)."""
    S = cfg.pp_stages
    L = cfg.num_hidden_layers
    assert L % S == 0, f"layers {L} not divisible by pp_stages {S}"
    return jax.tree_util.tree_map(
        lambda x: x.reshape((S, L // S) + x.shape[1:]), layer_params)


def forward_pipelined(params, tokens, cfg: LlamaConfig, mesh: Mesh):
    """Full pp×tp×sp×dp forward: embed → pipeline over stages → head."""
    if cfg.context_parallel != "none":
        raise NotImplementedError(
            "context_parallel with pp_stages > 1 is not supported yet: the "
            "pipeline stage loop would need the cp shard_map nested inside "
            "it; use cp with pp=1 (ring attention already gives the "
            "long-sequence memory scaling pipelining would)")
    sp_spec = (NamedSharding(mesh, P(None, "dp", "tp", None))
               if mesh.shape.get("tp", 1) > 1 else None)
    h = params["embed"].astype(cfg.dtype)[tokens]          # [B, T, D]
    h = microbatch(h, cfg.num_microbatches)                # [M, mb, T, D]
    h = lax.with_sharding_constraint(
        h, NamedSharding(mesh, P(None, "dp", "tp" if sp_spec is not None else None, None)))

    stage_params = _split_stages(params["layers"], cfg)

    def stage_fn(sp, x):
        inner_sp = sp_spec.spec if sp_spec is not None else None
        inner = NamedSharding(mesh, P(*inner_sp[1:])) if sp_spec is not None else None
        # per-layer remat inside the stage (same recompute FLOPs as
        # checkpointing the whole stage, but backward peak memory is one
        # layer's internals, not one stage's)
        return _scan_layers(sp, x, cfg, inner, remat=cfg.remat)

    h = pipeline_spmd(stage_fn, stage_params, h,
                      num_stages=cfg.pp_stages, remat=False)
    h = h.reshape((-1,) + h.shape[2:])                     # [B, T, D]
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    return _mm(h, params["lm_head"])


# ---------------------------------------------------------------------------
# loss / train step
# ---------------------------------------------------------------------------

def loss_fn(params, batch, cfg: LlamaConfig, mesh: Optional[Mesh] = None):
    """Next-token cross entropy via the fused op (ops/fused/cross_entropy):
    logits stay in model dtype and vocab-sharded (tp) end to end — no f32
    [B, T, V] log-softmax is materialised, and under GSPMD the reductions
    lower to the reference's _c_softmax_with_cross_entropy collective
    pattern (mp_ops.py:414), never a logits all-gather
    (tests/test_fused_ce.py checks the HLO)."""
    from ..ops.fused import fused_softmax_cross_entropy
    tokens, labels = batch["tokens"], batch["labels"]
    if mesh is not None and cfg.pp_stages > 1:
        logits = forward_pipelined(params, tokens, cfg, mesh)
    else:
        logits = forward(params, tokens, cfg, mesh)
        if _zigzag_on(cfg, mesh):
            # logits are in the zigzag layout; pair labels the same way
            from ..parallel.context_parallel import zigzag_global_perm
            labels = labels[:, zigzag_global_perm(labels.shape[1],
                                                  mesh.shape["cp"])]
    return fused_softmax_cross_entropy(logits, labels).mean()


from ..parallel.pipeline_async import PP_SCHEDULES

#: cfg.pp_schedule -> pipeline_async executor variant
ASYNC_PP_SCHEDULES = {k: var for k, (_, var) in PP_SCHEDULES.items()
                      if var is not None}


def _tp_local_block(lp, h, positions, cfg: LlamaConfig, attn_fn):
    """One transformer block on tp-LOCAL weight shards inside a
    ``shard_map`` body — the manual-collective mirror of ``_block``
    for the rank-asymmetric pipeline schedules, where GSPMD cannot
    insert the tp collectives (and a raw in-body ``lax.psum`` would
    transpose wrong under ``jax.vjp`` — parallel/mp_ops.py).

    Megatron placement: the "f" op (identity fwd, psum bwd) sits on
    each norm's OUTPUT, between the replicated math and the
    column-parallel weights — downstream of every replicated weight,
    so the backward psum completes the cotangent BEFORE it reaches the
    norm and its gradient arrives COMPLETE on each tp rank; the "g" op
    (psum fwd, identity bwd) completes the row-parallel outputs (wo,
    w_down) — two activation all-reduces per block forward and two
    backward, exactly the pattern the planner's analytic tp term
    priced. Local head/ffn widths are derived from the SHARD shapes
    (``wq.shape[-1] // head_dim``), so the same code runs at tp=1
    unsharded."""
    from ..parallel.mp_ops import (identity_fwd_psum_bwd,
                                   psum_fwd_identity_bwd)
    B, T, D = h.shape
    Dh = cfg.head_dim
    Hl = lp["wq"].shape[-1] // Dh
    Hkvl = lp["wk"].shape[-1] // Dh
    x = identity_fwd_psum_bwd(
        rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps), "tp")
    q = (x @ lp["wq"]).reshape(B, T, Hl, Dh)
    k = (x @ lp["wk"]).reshape(B, T, Hkvl, Dh)
    v = (x @ lp["wv"]).reshape(B, T, Hkvl, Dh)
    q, k = rope(q, k, positions, cfg.rope_theta, Dh)
    o = attn_fn(q, k, v)
    h = h + psum_fwd_identity_bwd(
        o.reshape(B, T, Hl * Dh) @ lp["wo"], "tp")
    x = identity_fwd_psum_bwd(
        rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps), "tp")
    h = h + psum_fwd_identity_bwd(
        (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"]))
        @ lp["w_down"], "tp")
    return h


def _async_stage_head_fns(cfg: LlamaConfig, mesh: Mesh):
    """(stage_fn, head_fn) for ``pipeline_train_async``'s shard_map
    body. tp=1 keeps the exact pre-r19 callables (GSPMD-free local
    math, fused dense CE) so those traced programs are unchanged;
    tp>1 switches to the manual-collective forms: ``_tp_local_block``
    per layer and a vocab-parallel head (``final_norm`` replicated,
    ``lm_head`` vocab-sharded, CE via the explicit-psum
    ``vocab_parallel_cross_entropy``)."""
    from ..ops.fused import (fused_softmax_cross_entropy,
                             vocab_parallel_cross_entropy)
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if tp <= 1:
        def stage_fn(chunk_params, xm):
            return _scan_layers(chunk_params, xm, cfg, None,
                                remat=cfg.remat)

        def head_fn(hp, y, y_labels):
            h = rms_norm(y, hp["final_norm"], cfg.rms_norm_eps)
            return fused_softmax_cross_entropy(
                h @ hp["lm_head"], y_labels).mean()
        return stage_fn, head_fn

    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    F, V = cfg.intermediate_size, cfg.vocab_size
    bad = {k: n for k, n in
           dict(heads=H, kv_heads=Hkv, ffn=F, vocab=V).items()
           if n % tp}
    if bad:
        raise ValueError(
            f"tp={tp} does not divide {bad} — the async schedules "
            f"shard heads/ffn/vocab over tp inside the stage body")
    from ..ops.pallas.flash_attention import flash_attention as _fa
    fa = cfg.use_flash_attention
    impl = fa if isinstance(fa, str) else ("auto" if fa else "dense")
    attn_fn = lambda q, k, v: _fa(q, k, v, causal=True, impl=impl)
    from ..parallel.mp_ops import identity_fwd_psum_bwd

    def stage_fn(chunk_params, xm):
        B, T, _ = xm.shape
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        fn = lambda lp, hh: _tp_local_block(lp, hh, positions, cfg,
                                            attn_fn)
        if cfg.remat:
            fn = jax.checkpoint(fn)

        def body(carry, lp):
            return fn(lp, carry), None

        h, _ = lax.scan(body, xm, chunk_params)
        return h

    def head_fn(hp, y, y_labels):
        h = identity_fwd_psum_bwd(
            rms_norm(y, hp["final_norm"], cfg.rms_norm_eps), "tp")
        return vocab_parallel_cross_entropy(
            h @ hp["lm_head"], y_labels, "tp").mean()
    return stage_fn, head_fn


def _async_shard_specs(cfg: LlamaConfig, mesh: Mesh):
    """(stage_specs, head_specs, x_spec, aux_specs) for the composed
    async executor: per-leaf chunk-dim specs derived from the ONE
    declared layout (``param_specs``), rows sharded over dp. The tail
    of each layer spec (everything after the stacked-layer axis) IS
    the chunk tail — the executor prepends its (V, pp) axes."""
    dp_on = mesh.shape.get("dp", 1) > 1
    tp_on = mesh.shape.get("tp", 1) > 1
    pspecs = param_specs(cfg)
    stage_specs = jax.tree_util.tree_map(
        lambda s: P(None, *(tuple(s)[1:] if tp_on else ())),
        pspecs["layers"], is_leaf=lambda v: isinstance(v, P))
    head_specs = {"final_norm": P(),
                  "lm_head": P(None, "tp") if tp_on else P()}
    dp_ax = "dp" if dp_on else None
    x_spec = P(None, dp_ax, None, None)
    aux_specs = P(None, dp_ax, None)
    return stage_specs, head_specs, x_spec, aux_specs


def grads_1f1b(params, batch, cfg: LlamaConfig, mesh: Mesh):
    """(loss, grads) via an explicit fused fwd+bwd pipeline schedule:
    the lockstep 1F1B / interleaved-VPP scan (parallel/pipeline_1f1b.py,
    ``pp_schedule="1f1b"``) or a rank-asymmetric schedule
    (parallel/pipeline_async.py, ``"1f1b_async"`` / ``"zb"`` — same
    numerics, reference per-rank bubble). Embedding forward+pullback
    bracket the pipeline; the loss head (final norm + lm_head + fused
    CE) runs per-microbatch as each one exits the last stage."""
    from ..ops.fused import fused_softmax_cross_entropy
    from ..parallel.pipeline_1f1b import (pipeline_train_1f1b,
                                          split_chunks_round_robin)
    from ..parallel.pipeline_async import pipeline_train_async
    S, V, M = cfg.pp_stages, cfg.vpp_chunks, cfg.num_microbatches
    tokens, labels = batch["tokens"], batch["labels"]
    tp_on = mesh is not None and mesh.shape.get("tp", 1) > 1
    inner_sp = (NamedSharding(mesh, P("dp", "tp", None)) if tp_on else None)
    mb_spec = P("dp", "tp" if tp_on else None, None)

    def stage_fn(chunk_params, xm):
        return _scan_layers(chunk_params, xm, cfg, inner_sp,
                            remat=cfg.remat)

    def head_fn(hp, y, y_labels):
        h = rms_norm(y, hp["final_norm"], cfg.rms_norm_eps)
        logits = h @ hp["lm_head"]
        return fused_softmax_cross_entropy(logits, y_labels).mean()

    def embed_fwd(emb):
        h = emb.astype(cfg.dtype)[tokens]
        return microbatch(h, M)

    x_mb, embed_pull = jax.vjp(embed_fwd, params["embed"])
    labels_mb = microbatch(labels, M)
    chunks = split_chunks_round_robin(
        params["layers"], cfg.num_hidden_layers, S, V)
    head_params = {"final_norm": params["final_norm"],
                   "lm_head": params["lm_head"]}
    if cfg.pp_schedule in ASYNC_PP_SCHEDULES:
        a_stage, a_head = _async_stage_head_fns(cfg, mesh)
        spec_kw = {}
        if (mesh.shape.get("dp", 1) > 1 or mesh.shape.get("tp", 1) > 1):
            sspecs, hspecs, xspec, aspecs = _async_shard_specs(cfg, mesh)
            spec_kw = dict(stage_specs=sspecs, head_specs=hspecs,
                           x_spec=xspec, aux_specs=aspecs)
        loss, gchunks, ghead, dx = pipeline_train_async(
            a_stage, a_head, chunks, head_params, x_mb, labels_mb,
            num_stages=S, virtual_chunks=V,
            variant=ASYNC_PP_SCHEDULES[cfg.pp_schedule], mesh=mesh,
            **spec_kw)
    else:
        loss, gchunks, ghead, dx = pipeline_train_1f1b(
            stage_fn, head_fn, chunks, head_params, x_mb, labels_mb,
            num_stages=S, virtual_chunks=V, mesh=mesh, mb_spec=mb_spec)
    glayers = jax.tree_util.tree_map(
        lambda g, p: g.reshape(p.shape), gchunks, params["layers"])
    (dembed,) = embed_pull(dx)
    grads = {"embed": dembed, "layers": glayers,
             "final_norm": ghead["final_norm"],
             "lm_head": ghead["lm_head"]}
    return loss, grads


def default_train_optimizer():
    """The optimizer ``make_train_step`` builds when none is given —
    one definition so the analysis targets (analysis/training_graphs.py)
    derive specs for the exact optimizer the step runs."""
    import optax
    return optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)


def train_state_specs(cfg: LlamaConfig, mesh: Mesh, optimizer=None,
                      zero_stage: int = 0):
    """PartitionSpec pytree matching ``make_train_step``'s state
    ``{"params", "opt", "step"}`` — the declared layout, computed
    without allocating anything. ``init_fn`` places by these specs and
    the static sharding lint reads the same tree, so the two cannot
    drift.

    Optimizer-state leaves inherit the owning param's (tp/pp) spec
    (every params-shaped subtree of the optax state maps one-to-one);
    zero_stage >= 1 layers a dp dim on top of each leaf's own spec via
    ``zero_spec``; zero_stage >= 3 does the same to the params.
    """
    from ..distributed.sharding import zero_spec
    if optimizer is None:
        optimizer = default_train_optimizer()
    dp = mesh.shape.get("dp", 1)
    pspecs = param_specs(cfg)
    abs_params = abstract_params(cfg)

    def add_zero(tree, abs_tree):
        def place(sp, a):
            if not getattr(a, "shape", None):
                return sp  # scalars (step counts) stay replicated
            zs = zero_spec(sp, a.shape, dp)
            return sp if zs is None else zs
        return jax.tree_util.tree_map(
            place, tree, abs_tree, is_leaf=lambda x: isinstance(x, P))

    # opt-state leaves mirror params subtree-by-subtree (adamw mu/nu);
    # anything not params-shaped (count scalars) replicates
    p_def = jax.tree_util.tree_structure(abs_params)
    abs_opt = jax.eval_shape(optimizer.init, abs_params)

    def params_like(node):
        try:
            return jax.tree_util.tree_structure(node) == p_def
        except Exception:
            return False

    opt_specs = jax.tree_util.tree_map(
        lambda node: pspecs if params_like(node) else P(),
        abs_opt, is_leaf=params_like)
    if zero_stage >= 1 and dp > 1:
        opt_specs = add_zero(opt_specs, abs_opt)
    if zero_stage >= 3 and dp > 1:
        pspecs = add_zero(pspecs, abs_params)
    return {"params": pspecs, "opt": opt_specs, "step": P()}


def make_train_step(cfg: LlamaConfig, mesh: Mesh, optimizer=None,
                    zero_stage: int = 0):
    """Build the jitted SPMD train step (fwd+bwd+adamw) over ``mesh``.

    Returns (step_fn, init_fn). ``init_fn(key)`` places params and
    optimizer state sharded on the mesh per ``train_state_specs``;
    ``step_fn(state, batch)`` is one update (state donated — params and
    optimizer buffers are updated in place, never doubly resident).

    zero_stage (reference: fleet group-sharded stages,
    dygraph_sharding_optimizer.py:48 / group_sharded_stage3.py):
      0 — optimizer state inherits the param (tp/pp) sharding only.
      1 — optimizer moments additionally sharded over dp (ZeRO-1).
      2 — same layout as 1; gradients arrive reduce-scattered into the
          dp-sharded layout because the only consumer (the sharded
          update) demands it — asserted on HLO in tests.
      3 — parameters themselves dp-sharded too; GSPMD all-gathers at
          use (ZeRO-3).
    """
    import optax
    if optimizer is None:
        optimizer = default_train_optimizer()
    if zero_stage not in (0, 1, 2, 3):
        raise ValueError(f"zero_stage must be 0..3, got {zero_stage}")

    use_1f1b = cfg.pp_stages > 1 and cfg.pp_schedule in PP_SCHEDULES
    if cfg.pp_schedule not in ("gpipe",) + tuple(PP_SCHEDULES):
        raise ValueError(
            f"pp_schedule must be one of "
            f"{('gpipe',) + tuple(PP_SCHEDULES)}, got "
            f"{cfg.pp_schedule!r}")

    def init_fn(key):
        specs = train_state_specs(cfg, mesh, optimizer, zero_stage)
        params = jax.tree_util.tree_map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
            init_params(cfg, key), specs["params"])
        # moments are born directly in their declared (possibly
        # dp-sharded) layout: optimizer.init on unsharded params would
        # transiently hold 2x full param bytes replicated per device —
        # the exact peak ZeRO stages exist to avoid
        opt_shardings = jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), specs["opt"],
            is_leaf=lambda x: isinstance(x, P))
        opt_state = jax.jit(optimizer.init,
                            out_shardings=opt_shardings)(params)
        return {"params": params, "opt": opt_state,
                "step": jax.device_put(
                    jnp.zeros((), jnp.int32),
                    NamedSharding(mesh, specs["step"]))}

    # ZeRO-3 rebuild-on-forward (group_sharded_stage3.py): compute runs
    # on params gathered back to their tp/pp-only layout; only STORAGE
    # (the state between steps) is dp-sharded. Besides being the
    # reference semantics, this keeps dp-sharded weights out of the
    # differentiated layer scan, which the CPU SPMD partitioner
    # miscompiles (fwd+bwd loss drifts 3e-3 from the f64 reference —
    # pinned by tests/test_zero_sharding.py numerics tests).
    fwd_pspecs = param_specs(cfg) if zero_stage >= 3 else None
    stored_pspecs = (train_state_specs(cfg, mesh, optimizer,
                                       zero_stage)["params"]
                     if zero_stage >= 3 else None)

    def _constrain(params, specs):
        return jax.tree_util.tree_map(
            lambda x, sp: lax.with_sharding_constraint(
                x, NamedSharding(mesh, sp)), params, specs)

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, batch):
        params = state["params"]
        if zero_stage >= 3:
            params = _constrain(params, fwd_pspecs)
        if use_1f1b:
            loss, grads = grads_1f1b(params, batch, cfg, mesh)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, cfg, mesh)
        updates, opt = optimizer.update(grads, state["opt"], params)
        params = optax.apply_updates(params, updates)
        if zero_stage >= 3:
            params = _constrain(params, stored_pspecs)
        return {"params": params, "opt": opt,
                "step": state["step"] + 1}, loss

    return step_fn, init_fn


# ---------------------------------------------------------------------------
# decode: KV cache + generate
# ---------------------------------------------------------------------------
# Reference capability: the fused decode attention + cache machinery
# (paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
# masked_multihead_attention_kernel.cu) behind paddle.incubate fused
# generation. TPU-native shape: the cache is a [L, B, S_max, Hkv, Dh]
# pytree updated with lax.dynamic_update_slice inside one jitted step;
# prefill reuses the flash kernel on the un-padded prompt, decode steps
# run a masked dense attention over the cache (T=1 queries cannot fill
# the MXU; the op is bandwidth-bound either way).


def init_kv_cache(cfg: LlamaConfig, batch_size: int, max_len: int):
    """Empty per-layer K/V cache, layers stacked on a leading axis."""
    L, Hkv, Dh = cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim
    shape = (L, batch_size, max_len, Hkv, Dh)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def _cached_attention(q, ck, cv, pos0, cfg: LlamaConfig):
    """q [B,T,H,Dh] against the full cache [B,S,Hkv,Dh]; query at
    position pos0+t attends to keys at positions <= pos0+t.

    GQA is a grouped einsum against the UN-repeated cache — decode is
    bandwidth-bound, so materialising an H-head copy of the cache would
    amplify its traffic H/Hkv-fold per step."""
    B, T, H, Dh = q.shape
    S, Hkv = ck.shape[1], ck.shape[2]
    G = H // Hkv
    qg = q.reshape(B, T, Hkv, G, Dh)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, ck) / np.sqrt(Dh)
    key_pos = jnp.arange(S)[None, :]                       # [1, S]
    q_pos = pos0 + jnp.arange(T)[:, None]                  # [T, 1]
    mask = key_pos <= q_pos                                # [T, S]
    scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    o = jnp.einsum("bkgts,bskd->btkgd", probs, cv)
    return o.reshape(B, T, H, Dh)


def forward_with_cache(params, tokens, cache, pos0, cfg: LlamaConfig):
    """tokens [B, T] at absolute positions pos0..pos0+T-1 -> (logits of
    the LAST position [B, V], updated cache). Used for both prefill
    (T = prompt length, pos0 = 0) and decode steps (T = 1)."""
    B, T = tokens.shape
    h = params["embed"].astype(cfg.dtype)[tokens]
    positions = pos0 + jnp.broadcast_to(jnp.arange(T), (B, T))
    is_prefill = isinstance(pos0, int) and pos0 == 0

    def body(h, xs):
        lp, ck, cv = xs
        cell = {}

        def attn_fn(q, k, v):
            ck2 = lax.dynamic_update_slice(
                ck, k.astype(ck.dtype), (0, pos0, 0, 0))
            cv2 = lax.dynamic_update_slice(
                cv, v.astype(cv.dtype), (0, pos0, 0, 0))
            cell["ck"], cell["cv"] = ck2, cv2
            if is_prefill:
                # prompt: plain causal attention over the fresh keys —
                # the flash kernel path, no cache-length masking needed
                from ..ops.pallas.flash_attention import (
                    flash_attention as _fa)
                fa = cfg.use_flash_attention
                impl = (fa if isinstance(fa, str)
                        else ("auto" if fa else "dense"))
                return _fa(q, k, v, causal=True, impl=impl)
            return _cached_attention(q, ck2, cv2, pos0, cfg)

        h = _block(lp, h, positions, cfg, attn_fn)
        return h, (cell["ck"], cell["cv"])

    h, (ck_new, cv_new) = lax.scan(
        body, h, (params["layers"], cache["k"], cache["v"]))
    h = rms_norm(h[:, -1], params["final_norm"], cfg.rms_norm_eps)
    logits = _mm(h, params["lm_head"])
    return logits.astype(jnp.float32), {"k": ck_new, "v": cv_new}


def sample_logits(logits, key, temperature: float = 1.0,
                  top_p: float = 1.0, top_k: int = 0):
    """[B, V] logits -> [B] token ids (greedy when temperature == 0)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with mass >= top_p; the top-1 token is
        # always kept (top_p=0.0 must degrade to greedy, not to
        # full-distribution sampling)
        keep = (cum - probs) < top_p
        keep = keep.at[:, 0].set(True)
        # cutoff = SMALLEST kept logit (min, not max — the max would mask
        # everything below the argmax and silently degenerate to greedy)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1)
        logits = jnp.where(logits < cutoff[:, None], -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _fused_sample(logits, temp, top_p, top_k, key, idx):
    """In-graph per-row sampling head of the serving tick (r16): the
    generalization of the fused argmax that lets SAMPLING requests
    ride the same programs as greedy ones. Greedy rows (temp == 0)
    take ``jnp.argmax`` — BITWISE the pre-r16 fused path, so every
    greedy==generate() pin survives; sampling rows apply temperature →
    top-k → top-p masking (``sample_logits`` semantics, but per-row
    DATA instead of static kwargs) and draw one gumbel/categorical
    token.

    Determinism discipline: the draw for a slot's token at
    continuation index ``idx[s]`` uses ``fold_in(key[s], idx[s])`` —
    the token INDEX keys the draw, not a split chain advanced per
    device step. A fixed seed therefore emits one token stream
    whatever the batch composition, fused-block boundaries or
    speculation around it: tokens a fused block computed past EOS, or
    drafts a verify rejected, burn no key state — the next launch
    re-draws the same index with the same key.

    logits ``[S, V]`` f32; temp/top_p ``[S]`` f32; top_k ``[S]`` i32
    (0 = filter off); key ``[S, 2]`` u32 raw per-slot PRNG keys; idx
    ``[S]`` i32. Returns ``[S]`` i32.

    Cost discipline: the whole sampling branch (sort, cumsum,
    categorical) sits behind a ``lax.cond`` on ``any(temp > 0)`` —
    still ONE program (the predicate is data), but an all-greedy tick
    executes only the argmax at runtime, so folding sampling into
    every program does not tax greedy traffic (measured: the sort is
    the dominant cost on the CPU mesh)."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _draw(_):
        l = logits / jnp.maximum(temp, 1e-6)[:, None]
        # top-k with k as data: cutoff at the k-th largest (k=0/off ->
        # the smallest value, masking nothing; ties at the cutoff
        # survive, matching sample_logits)
        srt = jnp.sort(l, axis=-1)[:, ::-1]
        k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, V), V)
        kth = jnp.take_along_axis(srt, (k_eff - 1)[:, None], axis=-1)
        # top-p over the top-k-masked logits (sample_logits order).
        # ONE sort suffices: the masked row's descending sort is the
        # original sort with sub-cutoff positions replaced (ties at
        # the cutoff survive masking in both views). The top-1 token
        # is always kept so top_p=0 degrades to greedy, and cutoff is
        # the SMALLEST kept logit.
        srt2 = jnp.where(srt >= kth, srt, -1e30)
        probs = jax.nn.softmax(srt2, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p[:, None]
        keep = keep.at[:, 0].set(True)
        cutoff = jnp.min(jnp.where(keep, srt2, jnp.inf), axis=-1)
        masked = jnp.where(l < kth, -1e30, l)
        masked = jnp.where(masked < cutoff[:, None], -1e30, masked)

        def draw(k, n, row):
            return jax.random.categorical(jax.random.fold_in(k, n), row)

        return jax.vmap(draw)(key, idx, masked).astype(jnp.int32)

    sampled = jax.lax.cond(jnp.any(temp > 0.0), _draw,
                           lambda _: greedy, None)
    return jnp.where(temp <= 0.0, greedy, sampled)


def _decode_loop(fwd_cache_fn, init_cache_fn, params, prompt,
                 max_new_tokens: int, temperature, top_p, top_k, key,
                 eos_token_id):
    """Shared autoregressive decode driver (llama + qwen2_moe): prefill
    via ``fwd_cache_fn(params, tokens, cache, pos0)``, then a scan of
    single-token steps with EOS latching. Returns prompt+continuation."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, "
                         f"got {max_new_tokens}")
    B, T0 = prompt.shape
    key = key if key is not None else jax.random.PRNGKey(0)
    cache = init_cache_fn(B, T0 + max_new_tokens)
    logits, cache = fwd_cache_fn(params, prompt, cache, 0)
    key, sub = jax.random.split(key)
    tok = sample_logits(logits, sub, temperature, top_p, top_k)
    done = (jnp.zeros((B,), bool) if eos_token_id is None
            else tok == eos_token_id)

    def step(carry, _):
        tok, cache, pos, key, done = carry
        logits, cache = fwd_cache_fn(params, tok[:, None], cache, pos)
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, temperature, top_p, top_k)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, cache, pos + 1, key, done), tok

    (last, _, _, _, _), toks = lax.scan(
        step, (tok, cache, jnp.int32(T0), key, done),
        None, length=max_new_tokens - 1)
    return jnp.concatenate(
        [prompt, jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)


def generate(params, prompt, cfg: LlamaConfig, max_new_tokens: int,
             *, temperature: float = 0.0, top_p: float = 1.0,
             top_k: int = 0, key=None, eos_token_id: Optional[int] = None):
    """Autoregressive decode with a KV cache.

    prompt: int32 [B, T0]. Returns [B, T0 + max_new_tokens] (prompt +
    continuation; positions after EOS repeat EOS when eos_token_id set).
    """
    return _decode_loop(
        lambda p, t, c, pos: forward_with_cache(p, t, c, pos, cfg),
        lambda B, L: init_kv_cache(cfg, B, L),
        params, prompt, max_new_tokens, temperature, top_p, top_k, key,
        eos_token_id)


# ---------------------------------------------------------------------------
# paged decode: block-table KV cache
# ---------------------------------------------------------------------------
# Reference: block_multi_head_attention (paged KV decode,
# paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu +
# python/paddle/incubate/nn/functional/block_multihead_attention.py).
# TPU shape: per-layer page pools [L, Hkv, P, ps, Dh] + shared tables,
# written with masked scatters; attention reads only each sequence's
# valid pages (inference/paged_kv.py — pallas kernel on TPU). Mixed-
# length batches stop paying the dense cache's B*max_len traffic.


def prefill_paged(params, tokens, lengths, cfg: LlamaConfig,
                  max_new_tokens: int, page_size: int = 16,
                  attn_impl: str = "auto"):
    """Ragged prefill: ``tokens [B, T0]`` right-padded, ``lengths [B]``
    valid counts. Builds the paged cache (prompt pages by PURE RESHAPE —
    measured: per-sequence page scatters cost ~14 ms/step on TPU — plus
    an empty dense tail for generated tokens) and returns (logits at
    each sequence's LAST valid position ``[B, V]``, cache)."""
    from ..inference.paged_kv import prompt_pages_from_dense
    from ..ops.pallas.flash_attention import flash_attention as _fa
    B, T0 = tokens.shape
    Hkv, Dh = cfg.num_key_value_heads, cfg.head_dim
    lengths = jnp.asarray(lengths, jnp.int32)
    h = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T0), (B, T0))
    if attn_impl != "auto":
        impl = attn_impl  # explicit override wins (decode honors it too)
    else:
        fa = cfg.use_flash_attention
        impl = fa if isinstance(fa, str) else ("auto" if fa else "dense")

    def body(h, lp):
        cell = {}

        def attn_fn(q, k, v):
            kp, vp, tables = prompt_pages_from_dense(
                k.astype(cfg.dtype), v.astype(cfg.dtype), page_size)
            cell["kp"], cell["vp"], cell["tables"] = kp, vp, tables
            # causal flash over the fresh prompt keys; pad positions
            # compute garbage that is never read (beyond-length pages
            # are masked by the kernel's length mask, their logits are
            # discarded)
            return _fa(q, k, v, causal=True, impl=impl)

        h = _block(lp, h, positions, cfg, attn_fn)
        return h, (cell["kp"], cell["vp"], cell["tables"])

    h, (k_pages, v_pages, tables) = lax.scan(body, h, params["layers"])
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.maximum(lengths - 1, 0)[:, None, None]
    h_last = jnp.take_along_axis(h, idx, axis=1)[:, 0]     # [B, D]
    logits = _mm(h_last, params["lm_head"])
    L = cfg.num_hidden_layers
    nt = max(max_new_tokens, 1)
    cache = {"k_pages": k_pages, "v_pages": v_pages,
             "tables": tables[0],        # identical across layers
             "prompt_lens": lengths,
             "k_tail": jnp.zeros((L, B, nt, Hkv, Dh), cfg.dtype),
             "v_tail": jnp.zeros((L, B, nt, Hkv, Dh), cfg.dtype),
             "n_tail": jnp.zeros((), jnp.int32)}
    return logits.astype(jnp.float32), cache


def _decode_paged_step(params, tok, cache, cfg: LlamaConfig,
                       attn_impl: str = "auto"):
    """One paged decode step: ``tok [B]`` -> (logits ``[B, V]``, cache).

    The token is appended to the dense TAIL (one lockstep
    dynamic_update_slice — no page scatter); attention merges the
    paged prompt with the live tail (paged_attention_with_tail)."""
    from ..inference.paged_kv import paged_attention_with_tail
    lens0 = cache["prompt_lens"]
    n = cache["n_tail"]
    h = params["embed"].astype(cfg.dtype)[tok[:, None]]     # [B, 1, D]
    positions = (lens0 + n)[:, None]

    def body(h, xs):
        lp, kp, vp, kt, vt = xs
        cell = {}

        def attn_fn(q, k, v):
            kt2 = lax.dynamic_update_slice(
                kt, k.astype(kt.dtype), (0, n, 0, 0))
            vt2 = lax.dynamic_update_slice(
                vt, v.astype(vt.dtype), (0, n, 0, 0))
            cell["kt"], cell["vt"] = kt2, vt2
            o = paged_attention_with_tail(
                q[:, 0], kp, vp, lens0, cache["tables"], kt2, vt2,
                n + 1, impl=attn_impl)
            return o[:, None].astype(q.dtype)

        h = _block(lp, h, positions, cfg, attn_fn)
        return h, (cell["kt"], cell["vt"])

    h, (kt_new, vt_new) = lax.scan(
        body, h, (params["layers"], cache["k_pages"], cache["v_pages"],
                  cache["k_tail"], cache["v_tail"]))
    h = rms_norm(h[:, 0], params["final_norm"], cfg.rms_norm_eps)
    logits = _mm(h, params["lm_head"])
    cache = dict(cache, k_tail=kt_new, v_tail=vt_new, n_tail=n + 1)
    return logits.astype(jnp.float32), cache


def generate_paged(params, prompt, lengths, cfg: LlamaConfig,
                   max_new_tokens: int, *, page_size: int = 16,
                   temperature: float = 0.0, top_p: float = 1.0,
                   top_k: int = 0, key=None,
                   eos_token_id: Optional[int] = None,
                   attn_impl: str = "auto"):
    """Batched autoregressive decode over the paged KV cache.

    prompt: int32 ``[B, T0]`` right-padded; lengths: valid counts
    ``[B]``. Returns the ``[B, max_new_tokens]`` continuations (ragged
    prompts make a concatenated return ill-defined; callers splice at
    ``lengths[b]``).
    """
    B, T0 = prompt.shape
    lengths = jnp.asarray(lengths, jnp.int32)
    key = key if key is not None else jax.random.PRNGKey(0)
    logits, cache = prefill_paged(params, prompt, lengths, cfg,
                                  max_new_tokens, page_size, attn_impl)
    key, sub = jax.random.split(key)
    tok = sample_logits(logits, sub, temperature, top_p, top_k)
    done = (jnp.zeros((B,), bool) if eos_token_id is None
            else tok == eos_token_id)

    def step(carry, _):
        tok, cache, key, done = carry
        logits, cache = _decode_paged_step(params, tok, cache, cfg,
                                           attn_impl)
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, temperature, top_p, top_k)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, cache, key, done), tok

    (last, _, _, _), toks = lax.scan(step, (tok, cache, key, done),
                                     None, length=max_new_tokens - 1)
    return jnp.concatenate([jnp.moveaxis(toks, 0, 1), last[:, None]],
                           axis=1)


# ---------------------------------------------------------------------------
# serving: single-step prefill/decode over a SHARED page pool
# ---------------------------------------------------------------------------
# The continuous-batching engine (paddle_tpu/serving/) needs step
# functions it can call once per tick against a persistent per-layer
# page pool — unlike generate_paged, whose cache is built fresh per
# batch and whose decode loop is fused into one scan. Pages here are
# allocated per REQUEST by the host-side PagePool (serving/scheduler.py)
# and freed the moment a sequence retires, so a long generation never
# holds cache capacity hostage for the whole batch. The block math is
# _block — the same single source of truth the training and fused-scan
# decode paths use.


def init_serving_pages(cfg, total_pages: int, page_size: int):
    """Layer-stacked page pools ``[L, Hkv, P, ps, Dh]`` (page 0 = trash)."""
    L, Hkv, Dh = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                  cfg.head_dim)
    shape = (L, Hkv, total_pages, page_size, Dh)
    return {"k_pages": jnp.zeros(shape, cfg.dtype),
            "v_pages": jnp.zeros(shape, cfg.dtype)}


def serving_prefill(params, tokens, length, table, k_pages, v_pages, cfg,
                    attn_impl: str = "auto", _block_fn=None):
    """Prefill ONE request into its allocated pages.

    tokens ``[1, Tb]`` right-padded to a compile bucket; length scalar
    i32 (valid tokens); table ``[pps]`` i32 — the slot's page-table row
    (trailing entries may be TRASH). k_pages/v_pages: the layer-stacked
    pools. Returns ``(logits [V] f32 at the last valid position,
    k_pages', v_pages')``. Padding positions write to the trash page and
    never influence valid logits (causal attention).
    """
    from ..inference.paged_kv import write_prompt_pages
    from ..ops.pallas.flash_attention import flash_attention as _fa
    block_fn = _block_fn if _block_fn is not None else _block
    B, T0 = tokens.shape
    lengths = jnp.reshape(length, (1,)).astype(jnp.int32)
    tables = jnp.reshape(table, (1, -1)).astype(jnp.int32)
    h = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T0), (B, T0))
    if attn_impl != "auto":
        impl = attn_impl
    else:
        fa = cfg.use_flash_attention
        impl = fa if isinstance(fa, str) else ("auto" if fa else "dense")

    def body(h, xs):
        lp, kp, vp = xs
        cell = {}

        def attn_fn(q, k, v):
            kp2, vp2 = write_prompt_pages(
                kp, vp, k.astype(kp.dtype), v.astype(vp.dtype), lengths,
                tables)
            cell["kp"], cell["vp"] = kp2, vp2
            return _fa(q, k, v, causal=True, impl=impl)

        h = block_fn(lp, h, positions, cfg, attn_fn)
        return h, (cell["kp"], cell["vp"])

    h, (kp_new, vp_new) = lax.scan(body, h, (params["layers"], k_pages,
                                             v_pages))
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.maximum(lengths - 1, 0)[:, None, None]
    h_last = jnp.take_along_axis(h, idx, axis=1)[:, 0]
    logits = _mm(h_last, params["lm_head"])
    return logits[0].astype(jnp.float32), kp_new, vp_new


def serving_prefill_chunk(params, tokens, length, table, k_pages, v_pages,
                          cfg, prefix_pages: int, attn_impl: str = "auto",
                          _block_fn=None):
    """Prefill ONE chunk of a request's prompt at a page-aligned offset.

    tokens ``[1, Tc]`` right-padded chunk; length scalar i32 (valid
    tokens IN the chunk); table ``[pps]`` i32 — the slot's full page-table
    row. ``prefix_pages`` (STATIC — one compile per value) is the number
    of pages already holding this request's earlier tokens: attached
    prefix-cache pages plus previously prefilled chunks. The chunk's
    first token sits at absolute position ``prefix_pages * page_size``
    (chunk boundaries are page-aligned by the engine: the chunk length
    and cache-attach granularity are both multiples of page_size).
    Returns ``(logits [V] f32 at the chunk's last valid position,
    k_pages', v_pages')``.

    Exactness: causal attention makes a prefix's KV a function of the
    prefix tokens alone, so the gathered pages hold exactly the bits a
    whole-prompt prefill would have produced for those positions; the
    chunk rows then see the same score rows (prefix gathered dense ++
    in-graph chunk, bottom-right causal mask) as the full flash program,
    and padding/width changes only add exact zeros to the reductions.
    Chunked, suffix-only and whole-prompt prefill therefore produce
    bitwise-identical KV and logits (tests/test_prefix_cache.py pins
    greedy equality through the engine in every cache state).
    """
    from ..inference.paged_kv import write_prompt_pages
    from ..ops.pallas.flash_attention import flash_attention as _fa
    block_fn = _block_fn if _block_fn is not None else _block
    prefix_pages = int(prefix_pages)
    B, Tc = tokens.shape
    Hkv, Dh = k_pages.shape[1], k_pages.shape[-1]
    ps = k_pages.shape[-2]
    off = prefix_pages * ps
    lengths = jnp.reshape(length, (1,)).astype(jnp.int32)
    tables = jnp.reshape(table, (1, -1)).astype(jnp.int32)
    pref_ids = tables[0, :prefix_pages]               # static length
    h = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(off + jnp.arange(Tc), (B, Tc))
    if attn_impl != "auto":
        impl = attn_impl
    else:
        fa = cfg.use_flash_attention
        impl = fa if isinstance(fa, str) else ("auto" if fa else "dense")

    def gather_prefix(pages):
        # [Hkv, n_pre, ps, Dh] -> [1, n_pre*ps, Hkv, Dh] (position-major)
        pre = pages[:, pref_ids].reshape(Hkv, off, Dh)
        return pre.transpose(1, 0, 2)[None]

    def body(h, xs):
        lp, kp, vp = xs
        cell = {}

        def attn_fn(q, k, v):
            kp2, vp2 = write_prompt_pages(
                kp, vp, k.astype(kp.dtype), v.astype(vp.dtype), lengths,
                tables, offset=off)
            cell["kp"], cell["vp"] = kp2, vp2
            if prefix_pages:
                kc = jnp.concatenate(
                    [gather_prefix(kp).astype(k.dtype), k], axis=1)
                vc = jnp.concatenate(
                    [gather_prefix(vp).astype(v.dtype), v], axis=1)
            else:
                kc, vc = k, v
            # bottom-right-aligned causal (S = off + Tc > Tc = T): every
            # chunk query attends the whole gathered prefix plus its own
            # causal window — _dense_reference's tril(k=S-T) / splash's
            # CausalMask(offset=S-T) implement exactly this
            return _fa(q, kc, vc, causal=True, impl=impl)

        h = block_fn(lp, h, positions, cfg, attn_fn)
        return h, (cell["kp"], cell["vp"])

    h, (kp_new, vp_new) = lax.scan(body, h, (params["layers"], k_pages,
                                             v_pages))
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.maximum(lengths - 1, 0)[:, None, None]
    h_last = jnp.take_along_axis(h, idx, axis=1)[:, 0]
    logits = _mm(h_last, params["lm_head"])
    return logits[0].astype(jnp.float32), kp_new, vp_new


def serving_decode_step(params, tok, lengths, tables, k_pages, v_pages,
                        cfg, attn_impl: str = "auto", _block_fn=None):
    """One decode tick for ALL slots of the serving batch.

    tok ``[S]`` i32 — each slot's current token; lengths ``[S]`` i32 —
    tokens already in that slot's cache (0 for dead slots, whose table
    rows are all-TRASH: they write to and read from the trash page and
    their logits are discarded by the host); tables ``[S, pps]``.
    Returns ``(logits [S, V] f32, k_pages', v_pages')``. The token's KV
    lands at position ``lengths[s]``; attention then covers
    ``lengths + 1`` positions — the paged counterpart of
    forward_with_cache's decode step.
    """
    from ..inference.paged_kv import paged_attention, write_token_pages
    block_fn = _block_fn if _block_fn is not None else _block
    h = params["embed"].astype(cfg.dtype)[tok[:, None]]      # [S, 1, D]
    positions = lengths[:, None]

    def body(h, xs):
        lp, kp, vp = xs
        cell = {}

        def attn_fn(q, k, v):
            kp2, vp2 = write_token_pages(
                kp, vp, k[:, 0].astype(kp.dtype), v[:, 0].astype(vp.dtype),
                lengths, tables)
            cell["kp"], cell["vp"] = kp2, vp2
            o = paged_attention(q[:, 0], kp2, vp2, lengths + 1, tables,
                                impl=attn_impl)
            return o[:, None].astype(q.dtype)

        h = block_fn(lp, h, positions, cfg, attn_fn)
        return h, (cell["kp"], cell["vp"])

    h, (kp_new, vp_new) = lax.scan(body, h, (params["layers"], k_pages,
                                             v_pages))
    h = rms_norm(h[:, 0], params["final_norm"], cfg.rms_norm_eps)
    logits = _mm(h, params["lm_head"])
    return logits.astype(jnp.float32), kp_new, vp_new


def serving_decode_block(params, tok, lengths, tables, k_pages, v_pages,
                         cfg, num_steps: int, attn_impl: str = "auto",
                         _block_fn=None):
    """``num_steps`` fused GREEDY decode ticks in one program (the
    multi-step scheduling lever: per-call dispatch + host bookkeeping
    amortize over the block). Sampling is in-graph argmax over the f32
    logits — bit-identical to sample_logits(temperature=0), so tokens
    still match single-step decode exactly. Returns
    ``(toks [S, num_steps] i32, k_pages', v_pages')``; the host
    truncates a sequence's tokens at EOS/max_new_tokens (positions a
    retiring sequence wrote past its budget land on the trash page via
    the table-width guard, so neighbours never see them)."""

    def step(carry, _):
        tok, lens, kp, vp = carry
        logits, kp, vp = serving_decode_step(
            params, tok, lens, tables, kp, vp, cfg, attn_impl, _block_fn)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, lens + 1, kp, vp), nxt

    (_, _, kp_new, vp_new), toks = lax.scan(
        step, (tok, lengths, k_pages, v_pages), None, length=num_steps)
    return jnp.moveaxis(toks, 0, 1), kp_new, vp_new


def serving_tick(params, tokens, meta, k_pages, v_pages, cfg, tq: int = 1,
                 decode_tail: int = 0, spec_k: int = 0,
                 attn_impl: str = "auto", _block_fn=None):
    """ONE ragged serving tick: any mix of chunked prefills, warm-prefix
    attaches and decode steps as a single static program.

    The pre-r12 engine dispatched separate geometry-bucketed programs
    (``serving_prefill`` per prompt bucket, ``serving_prefill_chunk``
    per static prefix_pages value, ``serving_decode_step``); this one
    step fn replaces all of them — sequence geometry rides in ``meta``
    as DEVICE ARRAYS, so XLA compiles exactly one program per packed
    width and the engine's compile-geometry quantization (chunk grids,
    attach quanta) is gone at the root.

    tokens ``[T]`` i32 — the tick's packed token stream: each live
    slot's current decode token and/or a span of some prompt's next
    uncached tokens, concatenated (padding tokens allowed anywhere).
    meta — a dict of device arrays describing the packing:

    * ``tok_slot [T]``: owning slot of each packed token (``S`` = a
      padding token that must touch nothing real);
    * ``tok_pos [T]``: the token's absolute sequence position;
    * ``tok_page [T]`` / ``tok_off [T]``: the page id and in-page
      offset its KV lands at (TRASH page for padding);
    * ``tok_qoff [T]``: offset of the token inside its slot's span;
    * ``q_len [S]``: span length per slot (0 = slot idle this tick);
    * ``kv_len [S]``: keys visible at the END of the span (context +
      the span itself);
    * ``last [T-indexed scalar per slot] [S]``: packed index of each
      slot's LAST span token — its hidden state feeds that slot's
      logits row (idle slots may point anywhere; their row is junk the
      host discards);
    * ``tables [S, pps]``: the page-table rows.

    FUSED SAMPLING (r16) — five more optional meta arrays, all DATA,
    turn every token selection in the tick (last-position pick, fused
    tail steps, speculative verify) into a per-slot
    temperature/top-k/top-p gumbel draw via ``_fused_sample``:
    ``temp [S]`` f32 / ``top_p [S]`` f32 / ``top_k [S]`` i32 (0 =
    off) / ``key [S, 2]`` u32 raw per-slot PRNG keys / ``produced
    [S]`` i32 — the continuation index of the token this launch
    emits; token ``n`` is always drawn with ``fold_in(key, n)``, so a
    fixed seed yields one stream whatever the batch composition,
    block fusion or speculation (see ``_fused_sample``). Greedy rows
    (temp == 0) keep the bitwise argmax. The engine ALWAYS passes
    these (presence is a trace-time fact): SAMPLING slots ride the
    same fused programs as greedy ones, and the pre-r16 width-S
    single-step sampling program is gone from the inventory.

    ``tq`` (STATIC — one compile per value; the engine uses exactly
    two: the prefill budget and 1) is the maximum span length, sizing
    the kernel's slot-major query layout.

    ``decode_tail`` (STATIC) fuses that many extra GREEDY decode steps
    after the ragged pass — the multi-step scheduling lever that keeps
    an admission tick producing a full decode block for in-flight
    streams (the seed engine got this by running prefill + the fused
    block as two programs; here the tail rides in the SAME program).
    ``meta['tail_live'] [S]`` bool gates it: only tail-live slots
    (decoding slots, plus spans that complete their prompt this tick)
    advance — mid-prefill slots stay dead through the tail (q_len 0,
    KV writes to the trash page).

    ``spec_k`` (STATIC — the engine's draft-length cap; one compile
    per value, and a speculative engine uses exactly one) turns the
    tick into the speculative VERIFY program: speculating slots
    submitted their current token plus up to ``spec_k`` draft tokens
    as an ordinary ragged span (the same packed stream, mixed with
    prefill spans and plain decode slots), and the tick additionally
    computes the target model's greedy argmax at EVERY span position
    plus the in-graph longest-prefix acceptance against the drafts.
    Three extra ``meta`` arrays carry the (per-slot, DATA-not-shape)
    speculation geometry:

    * ``ver_idx [S, 1+spec_k]``: packed index of each slot's span
      token ``j`` (position ``j``'s hidden state predicts span
      position ``j+1``); non-speculating slots point every entry at
      their ``last`` token, so their row 0 reproduces the plain
      tick's logits/argmax exactly;
    * ``draft_tok [S, spec_k]`` / ``draft_len [S]``: the draft tokens
      and each slot's actual draft count ``k_s <= spec_k`` (0 for
      non-speculating slots — adaptive k is data, the cap is the only
      shape).

    ``spec_k`` and ``decode_tail`` are mutually exclusive (speculation
    IS the multi-token lever on a speculative engine).

    Returns ``(toks, logits [S, V] f32, k_pages', v_pages')``:
    ``toks`` is each slot's in-graph token pick at its last position
    (argmax, or the fused sampler's draw) — ``[S]`` i32 when
    ``decode_tail == 0``, else ``[S, 1+decode_tail]`` (the host pulls
    only these ints, whoever samples); ``logits`` is the RAGGED
    pass's (first step's) logits, kept for OFFLINE callers that
    sample their own way — since r16 the engine never reads it (the
    fused sampler replaced the host path), it stays on device and is
    dropped. With ``spec_k > 0`` the
    return is ``(toks [S, 1+spec_k], accept [S], logits [S, V] f32,
    k_pages', v_pages')``: ``toks[s, j]`` is the target argmax after
    consuming span tokens ``0..j``, ``accept[s]`` the number of
    leading drafts matching it (``toks[s, :accept[s]]`` equal the
    drafts token-for-token and ``toks[s, accept[s]]`` is the bonus/
    correction token — ``1 + accept`` emitted tokens from ONE target
    launch), and ``logits`` is row 0's logits (``ver_idx[:, 0]``
    points at ``last`` for every slot a host would sample from).
    Rejected draft KV needs no device-side rollback: the stale rows
    sit past the slot's advanced length, masked by ``kv_len`` until
    the sequence's real tokens overwrite them positionally — the same
    trash-row discipline retiring overruns already rely on.

    Exactness: the span's KV is scattered into the pages FIRST, then
    the ragged kernel attends over pages only, bottom-right causal —
    so a prefix's KV is a function of the prefix tokens alone and
    chunked/whole/warm prefills all produce the bits a single
    whole-prompt pass would (tests pin greedy equality to
    ``generate()`` in every cache state).
    """
    from ..ops.pallas.ragged_paged_attention import (
        ragged_paged_attention_packed)
    block_fn = _block_fn if _block_fn is not None else _block
    tq = int(tq)
    spec_k = int(spec_k)
    decode_tail = int(decode_tail)
    if spec_k and decode_tail:
        raise ValueError("spec_k and decode_tail are mutually "
                         "exclusive (speculation replaces the "
                         "fused greedy tail)")
    S = meta["q_len"].shape[0]
    tok_slot = meta["tok_slot"]
    tok_qoff = meta["tok_qoff"]
    h = params["embed"].astype(cfg.dtype)[tokens[None]]        # [1, T, D]
    positions = meta["tok_pos"][None]

    def body(h, xs):
        lp, kp, vp = xs
        cell = {}

        def attn_fn(q, k, v):
            # 1) land the span's KV in the pages (padding -> trash page)
            kp2 = kp.at[:, meta["tok_page"], meta["tok_off"]].set(
                k[0].transpose(1, 0, 2).astype(kp.dtype))
            vp2 = vp.at[:, meta["tok_page"], meta["tok_off"]].set(
                v[0].transpose(1, 0, 2).astype(vp.dtype))
            cell["kp"], cell["vp"] = kp2, vp2
            # 2) one ragged launch over the pages (span KV included):
            # the packed entry keeps score work proportional to the T
            # real rows off-TPU and scatters to the kernel's slot-major
            # layout on TPU
            o = ragged_paged_attention_packed(
                q[0], kp2, vp2, tok_slot, tok_qoff, meta["q_len"],
                meta["kv_len"], meta["tables"], tq=tq, impl=attn_impl)
            return o[None].astype(q.dtype)

        h = block_fn(lp, h, positions, cfg, attn_fn)
        return h, (cell["kp"], cell["vp"])

    h, (kp_new, vp_new) = lax.scan(body, h, (params["layers"], k_pages,
                                             v_pages))
    h = rms_norm(h[0], params["final_norm"], cfg.rms_norm_eps)  # [T, D]
    # fused sampling (r16): when the meta carries per-slot sampling
    # state — temp/top_p [S] f32, top_k [S] i32, key [S, 2] u32 raw
    # PRNG keys, produced [S] i32 (the continuation index of the token
    # this launch emits) — every token selection below goes through
    # _fused_sample instead of bare argmax, so SAMPLING slots ride the
    # same program as greedy ones (the engine always passes the
    # fields; presence is a trace-time fact, not a per-tick branch).
    # Greedy rows still take the bitwise argmax path inside.
    samp = "temp" in meta

    def pick(logits, idx):
        if samp:
            return _fused_sample(logits, meta["temp"], meta["top_p"],
                                 meta["top_k"], meta["key"], idx)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    if spec_k:
        # logits at EVERY span position of every slot — the verify
        # pass's whole point: one launch prices 1+spec_k predictions
        h_ver = h[meta["ver_idx"]]                  # [S, 1+spec_k, D]
        logits_ver = _mm(h_ver, params["lm_head"]).astype(jnp.float32)
        if samp:
            # SAMPLED acceptance (spec_k is no longer greedy-only):
            # span position j draws the token for continuation index
            # produced+j — the same fold_in key a plain tick would
            # use at that index, and conditioning over the accepted
            # prefix is exact by construction, so the emitted stream
            # is bitwise the non-speculative engine's whatever the
            # drafter proposed. Greedy slots still argmax (temp==0).
            kk = 1 + spec_k
            idx = (meta["produced"][:, None]
                   + jnp.arange(kk, dtype=jnp.int32)[None]).reshape(-1)
            toks = _fused_sample(
                logits_ver.reshape(S * kk, -1),
                jnp.repeat(meta["temp"], kk),
                jnp.repeat(meta["top_p"], kk),
                jnp.repeat(meta["top_k"], kk),
                jnp.repeat(meta["key"], kk, axis=0),
                idx).reshape(S, kk)
        else:
            toks = jnp.argmax(logits_ver, axis=-1).astype(jnp.int32)
        # longest-prefix acceptance: draft j is accepted iff every
        # draft 0..j matched the target's token (sampled or argmax) at
        # its span position (cumprod zeroes everything after the first
        # mismatch) and j is a real draft (j < draft_len — adaptive k
        # is data)
        j = jnp.arange(spec_k)
        match = ((toks[:, :spec_k] == meta["draft_tok"])
                 & (j[None, :] < meta["draft_len"][:, None]))
        accept = jnp.cumprod(match.astype(jnp.int32), axis=1) \
                    .sum(axis=1).astype(jnp.int32)
        # row 0 == the plain tick's logits for every non-speculating
        # slot (ver_idx[:, 0] = last there)
        return toks, accept, logits_ver[:, 0], kp_new, vp_new
    h_last = h[meta["last"]]                                    # [S, D]
    logits = _mm(h_last, params["lm_head"]).astype(jnp.float32)
    toks = pick(logits, meta["produced"] if samp else None)
    if not decode_tail:
        return toks, logits, kp_new, vp_new

    ps = k_pages.shape[-2]
    pps = meta["tables"].shape[1]
    b_idx = jnp.arange(S, dtype=jnp.int32)
    zeros = jnp.zeros((S,), jnp.int32)
    live = meta["tail_live"].astype(jnp.bool_)

    def step(carry, _):
        tok, lens, idx, kp, vp = carry
        slot = lens // ps
        # rows out of pages (retiring overruns), dead all-TRASH rows
        # and tail-dead (mid-prefill) slots land on the trash page,
        # exactly like write_token_pages
        ok = live & (slot < pps)
        page = jnp.where(
            ok, meta["tables"][b_idx, jnp.minimum(slot, pps - 1)], 0)
        m = dict(tok_slot=jnp.where(live, b_idx, S).astype(jnp.int32),
                 tok_pos=lens, tok_page=page.astype(jnp.int32),
                 tok_off=jnp.where(ok, lens % ps, 0).astype(jnp.int32),
                 tok_qoff=zeros, q_len=live.astype(jnp.int32),
                 kv_len=lens + 1, last=b_idx, tables=meta["tables"])
        if samp:
            # step j of the tail samples continuation index
            # produced + j: the fold_in discipline, not a split chain
            m.update(temp=meta["temp"], top_p=meta["top_p"],
                     top_k=meta["top_k"], key=meta["key"],
                     produced=idx)
        nxt, _, kp, vp = serving_tick(params, tok, m, kp, vp, cfg,
                                      tq=1, attn_impl=attn_impl,
                                      _block_fn=_block_fn)
        return (nxt, lens + 1, idx + 1, kp, vp), nxt

    idx0 = (meta["produced"] + 1) if samp else zeros
    (_, _, _, kp_new, vp_new), tail = lax.scan(
        step, (toks, meta["kv_len"], idx0, kp_new, vp_new), None,
        length=decode_tail)
    toks = jnp.concatenate([toks[:, None], jnp.moveaxis(tail, 0, 1)],
                           axis=1)                    # [S, 1+tail]
    return toks, logits, kp_new, vp_new


def serving_tick_block(params, tok, lengths, tables, k_pages, v_pages,
                       cfg, num_steps: int, attn_impl: str = "auto",
                       _block_fn=None, sampling=None):
    """``num_steps`` fused decode ticks built on the ragged tick (the
    multi-step scheduling lever — same contract as the retired
    ``serving_decode_block``: greedy slots are in-graph argmax and
    match single-step decode exactly, dead slots write to and read
    from the trash page). tok/lengths ``[S]`` i32, tables
    ``[S, pps]``. ``sampling`` (r16): a dict of the fused-sampling
    meta arrays — ``temp``/``top_p`` f32 [S], ``top_k`` i32 [S],
    ``key`` u32 [S, 2], ``produced`` i32 [S] — letting SAMPLING slots
    ride the fused block too (step ``j`` draws continuation index
    ``produced + j`` via the fold_in discipline); None keeps the
    all-greedy block. Returns
    ``(toks [S, num_steps] i32, k_pages', v_pages')``."""
    S = tok.shape[0]
    pps = tables.shape[1]
    ps = k_pages.shape[-2]
    b_idx = jnp.arange(S, dtype=jnp.int32)
    slot = lengths // ps
    # rows out of pages (retiring overruns) and dead all-TRASH rows
    # land on the trash page, exactly like write_token_pages
    page = jnp.where(slot < pps,
                     tables[b_idx, jnp.minimum(slot, pps - 1)], 0)
    meta = dict(tok_slot=b_idx, tok_pos=lengths, tok_page=page,
                tok_off=lengths % ps, tok_qoff=jnp.zeros((S,), jnp.int32),
                q_len=jnp.ones((S,), jnp.int32), kv_len=lengths + 1,
                last=b_idx, tables=tables,
                tail_live=jnp.ones((S,), jnp.bool_))
    if sampling is not None:
        meta.update(temp=sampling["temp"], top_p=sampling["top_p"],
                    top_k=sampling["top_k"], key=sampling["key"],
                    produced=sampling["produced"])
    toks, _, kp_new, vp_new = serving_tick(
        params, tok, meta, k_pages, v_pages, cfg, tq=1,
        decode_tail=num_steps - 1, attn_impl=attn_impl,
        _block_fn=_block_fn)
    if num_steps == 1:
        toks = toks[:, None]
    return toks, kp_new, vp_new


def make_batch(cfg: LlamaConfig, batch_size: int, seq_len: int, mesh: Mesh,
               key=None):
    """Synthetic next-token batch, dp-sharded."""
    key = key if key is not None else jax.random.PRNGKey(0)
    toks = jax.random.randint(key, (batch_size, seq_len + 1), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    cp = "cp" if mesh.shape.get("cp", 1) > 1 else None
    sh = NamedSharding(mesh, P("dp", cp))
    return {"tokens": jax.device_put(toks[:, :-1], sh),
            "labels": jax.device_put(toks[:, 1:], sh)}

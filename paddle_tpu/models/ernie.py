"""ERNIE/BERT-style bidirectional encoder with pretrain + fine-tune heads.

SURVEY.md §7 step 10 names "ERNIE-style transformer fine-tune" as a
parity model. The reference framework ships the building blocks
(python/paddle/nn/layer/transformer.py) and the ERNIE model itself
lives in the Paddle ecosystem; this module provides the same shape:
token/position/segment embeddings -> pre-LN-free TransformerEncoder ->
pooler, with heads for masked-LM pretraining and sequence
classification fine-tune.

TPU notes: everything here jits cleanly (static shapes, no
data-dependent control flow); padding masks become additive -inf bias
on the attention logits. For multi-chip fine-tunes the Layer composes
with distributed.auto_parallel_api.shard_layer (column/row-split the
qkv/ffn Linears) the same way any Linear-based Layer does.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForPretraining"]


class ErnieConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id

    @classmethod
    def tiny(cls, **kw):
        base = dict(vocab_size=1000, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64)
        base.update(kw)
        return cls(**base)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        ids = input_ids.data if isinstance(input_ids, Tensor) else input_ids
        B, T = ids.shape
        if position_ids is None:
            position_ids = Tensor(jnp.broadcast_to(jnp.arange(T), (B, T)))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((B, T), jnp.int32))
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class ErnieModel(nn.Layer):
    """Encoder trunk: returns (sequence_output [B,T,D], pooled [B,D])."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob)
        self.encoder = nn.TransformerEncoder(layer, cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.pooler_act = nn.Tanh()

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        ids = input_ids.data if isinstance(input_ids, Tensor) else input_ids
        if attention_mask is None:
            attention_mask = Tensor(
                (ids != self.cfg.pad_token_id).astype(jnp.float32))
        am = (attention_mask.data if isinstance(attention_mask, Tensor)
              else jnp.asarray(attention_mask))
        if am.ndim == 2:  # [B,T] keep-mask -> [B,1,1,T] additive bias
            bias = (1.0 - am[:, None, None, :]) * -1e9
        else:
            bias = am
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(h, Tensor(bias))
        pooled = self.pooler_act(self.pooler(seq[:, 0]))
        return seq, pooled


class ErnieForSequenceClassification(nn.Layer):
    """Fine-tune head (reference-ecosystem surface:
    ErnieForSequenceClassification(ernie, num_classes, dropout))."""

    def __init__(self, ernie: ErnieModel, num_classes: int = 2,
                 dropout=None):
        super().__init__()
        self.ernie = ernie
        self.num_classes = num_classes
        self.dropout = nn.Dropout(
            dropout if dropout is not None
            else ernie.cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(ernie.cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.classifier(self.dropout(pooled))


class ErnieForPretraining(nn.Layer):
    """Masked-LM + next-sentence heads. MLM projection is tied to the
    word embedding matrix (standard ERNIE/BERT weight tying)."""

    def __init__(self, ernie: ErnieModel):
        super().__init__()
        self.ernie = ernie
        D = ernie.cfg.hidden_size
        self.transform = nn.Linear(D, D)
        self.transform_act = nn.GELU()
        self.transform_norm = nn.LayerNorm(D)
        self.mlm_bias = self.create_parameter(
            (ernie.cfg.vocab_size,), is_bias=True)
        self.nsp = nn.Linear(D, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        h = self.transform_norm(self.transform_act(self.transform(seq)))
        emb = self.ernie.embeddings.word_embeddings.weight  # [V, D]
        # registered ops only (matmul/transpose/add) — raw jnp on .data
        # would bypass the eager tape and freeze pretraining
        mlm_logits = h @ emb.t() + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


def mlm_loss(mlm_logits, labels, ignore_index: int = -100):
    """Masked-LM loss averaged over positions with label != ignore_index
    (tape-tracked: delegates to the fused vocab cross-entropy op)."""
    from ..nn import functional as F
    if not isinstance(labels, Tensor):
        labels = Tensor(jnp.asarray(labels))
    return F.cross_entropy(mlm_logits, labels, ignore_index=ignore_index,
                           reduction="mean")

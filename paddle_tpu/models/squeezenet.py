"""SqueezeNet 1.0 / 1.1.

Reference: python/paddle/vision/models/squeezenet.py (Fire module:
squeeze 1x1 -> expand 1x1 + 3x3 concat; same stage layouts).
"""
from __future__ import annotations

from .. import nn
from ._zoo import check_no_pretrained
from ..ops.manipulation import concat

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, expand1x1_c, expand3x3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1x1 = nn.Conv2D(squeeze_c, expand1x1_c, 1)
        self.expand3x3 = nn.Conv2D(squeeze_c, expand3x3_c, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        a = self.relu(self.expand1x1(x))
        b = self.relu(self.expand3x3(x))
        # registered concat keeps the autograd tape intact (raw
        # jnp.concatenate on .data would freeze everything upstream)
        return concat([a, b], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), Fire(512, 64, 256, 256))
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        else:
            raise ValueError(f"unsupported version {version!r}")
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    check_no_pretrained(pretrained)
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    check_no_pretrained(pretrained)
    return SqueezeNet("1.1", **kwargs)

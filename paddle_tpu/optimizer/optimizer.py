"""Optimizers (reference: python/paddle/optimizer/optimizer.py, adamw.py...).

Dual-mode design:
- imperative: ``opt.step()`` reads eager ``.grad`` and rebinds parameter
  storage — paddle UX parity.
- functional: the same pure-jnp per-parameter update math runs under jit
  tracing (state slots are Tensors whose storage the TrainStep lifting swaps
  for traced arrays), so a whole train step compiles to one XLA module with
  the optimizer fused in. This replaces the reference's per-op CUDA
  adam/momentum kernels (paddle/phi/kernels/gpu/adam_kernel.cu) with
  XLA-fused update code.

The learning rate is carried as a 0-d f32 Tensor so LR schedules don't force
recompilation (it's a traced input, not a baked constant).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..autograd import no_grad
from .lr import LRScheduler


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = coeff


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = coeff


class Optimizer:
    _slot_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if parameters is None:
            raise ValueError(
                "parameters must be given (paddle_tpu has no global "
                "parameter registry); pass model.parameters()")
        self._param_list = list(parameters)
        self._param_groups = None
        if self._param_list and isinstance(self._param_list[0], dict):
            groups = self._param_list
            self._param_groups = groups
            self._param_list = [p for g in groups for p in g["params"]]
        self._lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        init_lr = learning_rate() if self._lr_sched else float(learning_rate)
        self._lr = Tensor(jnp.asarray(init_lr, jnp.float32))
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[int, Tensor]] = {}
        self._step_count = Tensor(jnp.zeros((), jnp.int32))

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_sched is not None:
            return self._lr_sched()
        return float(np.asarray(self._lr._data))

    def set_lr(self, value):
        self._lr_sched = None
        self._lr._data = jnp.asarray(float(value), jnp.float32)

    def set_lr_scheduler(self, scheduler):
        self._lr_sched = scheduler

    def _sync_lr(self):
        if self._lr_sched is not None:
            self._lr._data = jnp.asarray(self._lr_sched(), jnp.float32)

    # -- state --------------------------------------------------------------
    def _acc(self, name: str, p: Parameter, init=None, dtype=None) -> Tensor:
        store = self._accumulators.setdefault(name, {})
        key = id(p)
        if key not in store:
            if init is None:
                dt = dtype or (jnp.float32 if self._use_master(p) else p._data.dtype)
                store[key] = Tensor(jnp.zeros(p._data.shape, dt))
            else:
                store[key] = Tensor(init)
        return store[key]

    def _use_master(self, p) -> bool:
        return self._multi_precision and p._data.dtype in (jnp.bfloat16,
                                                           jnp.float16)

    def _master(self, p: Parameter) -> Optional[Tensor]:
        if not self._use_master(p):
            return None
        store = self._accumulators.setdefault("master_weight", {})
        if id(p) not in store:
            store[id(p)] = Tensor(p._data.astype(jnp.float32))
        return store[id(p)]

    def _all_state_tensors(self) -> List[Tensor]:
        out = [self._lr, self._step_count]
        for store in self._accumulators.values():
            out.extend(store.values())
        return out

    def state_dict(self):
        out = {"LR_Scheduler": (self._lr_sched.state_dict()
                                if self._lr_sched else {"lr": self.get_lr()}),
               "step_count": int(np.asarray(self._step_count._data))}
        id2name = {}
        for i, p in enumerate(self._param_list):
            id2name[id(p)] = p.name or f"param_{i}"
        for slot, store in self._accumulators.items():
            for pid, t in store.items():
                if pid in id2name:
                    out[f"{id2name[pid]}_{slot}"] = t
        return out

    def set_state_dict(self, state):
        id2name = {}
        for i, p in enumerate(self._param_list):
            id2name[id(p)] = p.name or f"param_{i}"
        for slot, store in self._accumulators.items():
            for pid in store:
                key = f"{id2name.get(pid)}_{slot}"
                if key in state:
                    v = state[key]
                    store[pid]._data = (v._data if isinstance(v, Tensor)
                                        else jnp.asarray(np.asarray(v)))
        if "LR_Scheduler" in state and self._lr_sched is not None:
            self._lr_sched.set_state_dict(state["LR_Scheduler"])
        if "step_count" in state:
            self._step_count._data = jnp.asarray(int(state["step_count"]),
                                                 jnp.int32)

    # -- stepping -----------------------------------------------------------
    @no_grad()
    def step(self, _sync_lr: bool = True):
        # _sync_lr=False: caller already synced the scheduler host-side —
        # the auto-parallel Engine's jitted step does this so the traced
        # program reads the lr from its input instead of baking the
        # trace-time scheduler value in as a constant
        if _sync_lr:
            self._sync_lr()
        params_grads = []
        for p in self._param_list:
            if p.stop_gradient or p._grad is None:
                continue
            params_grads.append((p, p._grad._data))
        if self._grad_clip is not None:
            clipped = self._grad_clip(params_grads)
            params_grads = [(p, g) for (p, _), (_, g) in
                            zip(params_grads, clipped)]
        self._step_count._data = self._step_count._data + 1
        lr = self._lr._data
        for p, g in params_grads:
            master = self._master(p)
            wd = self._decay_coeff(p)
            self._apply_one(p, g, lr, master, wd)

    def _decay_coeff(self, p) -> float:
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if isinstance(wd, (L2Decay, L1Decay)):
            return wd.coeff
        return float(wd)

    def _apply_one(self, p, g, lr, master, wd):
        raise NotImplementedError

    @no_grad()
    def clear_grad(self, set_to_zero: bool = False):
        for p in self._param_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def _param_update(self, p, master, new_value_f32):
        """Write back an fp32 update into (master, param) respecting dtype."""
        if master is not None:
            master._data = new_value_f32
            p._data = new_value_f32.astype(p._data.dtype)
        else:
            p._data = new_value_f32.astype(p._data.dtype)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _apply_one(self, p, g, lr, master, wd):
        w = master._data if master is not None else p._data
        g = g.astype(w.dtype)
        if wd:
            g = g + wd * w
        self._param_update(p, master, w - lr.astype(w.dtype) * g)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _apply_one(self, p, g, lr, master, wd):
        w = master._data if master is not None else p._data
        g = g.astype(w.dtype)
        if wd:
            g = g + wd * w
        v = self._acc("velocity", p)
        v._data = self._momentum * v._data.astype(w.dtype) + g
        if self._nesterov:
            upd = g + self._momentum * v._data
        else:
            upd = v._data
        self._param_update(p, master, w - lr.astype(w.dtype) * upd)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._decoupled_wd = False  # Adam couples decay into grads (L2)

    def _apply_one(self, p, g, lr, master, wd):
        w32 = (master._data if master is not None else p._data).astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        if wd and not self._decoupled_wd:
            g32 = g32 + wd * w32
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        t = self._step_count._data.astype(jnp.float32)
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        v._data = self._beta2 * v._data + (1 - self._beta2) * jnp.square(g32)
        mhat = m._data / (1 - jnp.power(self._beta1, t))
        vhat = v._data / (1 - jnp.power(self._beta2, t))
        lr32 = lr.astype(jnp.float32)
        new_w = w32 - lr32 * mhat / (jnp.sqrt(vhat) + self._eps)
        if wd and self._decoupled_wd:
            new_w = new_w - lr32 * wd * w32
        self._param_update(p, master, new_w)


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name=name)
        self._decoupled_wd = True
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_coeff(self, p):
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            return 0.0
        return super()._decay_coeff(p)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _apply_one(self, p, g, lr, master, wd):
        w32 = (master._data if master is not None else p._data).astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * w32
        acc = self._acc("moment", p,
                        init=jnp.full(p._data.shape, self._init_acc,
                                      jnp.float32))
        acc._data = acc._data + jnp.square(g32)
        new_w = w32 - lr.astype(jnp.float32) * g32 / (
            jnp.sqrt(acc._data) + self._eps)
        self._param_update(p, master, new_w)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho = rho
        self._eps = epsilon
        self._momentum = momentum
        self._centered = centered

    def _apply_one(self, p, g, lr, master, wd):
        w32 = (master._data if master is not None else p._data).astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * w32
        ms = self._acc("mean_square", p, dtype=jnp.float32)
        ms._data = self._rho * ms._data + (1 - self._rho) * jnp.square(g32)
        if self._centered:
            mg = self._acc("mean_grad", p, dtype=jnp.float32)
            mg._data = self._rho * mg._data + (1 - self._rho) * g32
            denom = jnp.sqrt(ms._data - jnp.square(mg._data) + self._eps)
        else:
            denom = jnp.sqrt(ms._data + self._eps)
        upd = lr.astype(jnp.float32) * g32 / denom
        if self._momentum:
            mom = self._acc("momentum", p, dtype=jnp.float32)
            mom._data = self._momentum * mom._data + upd
            upd = mom._data
        self._param_update(p, master, w32 - upd)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon

    def _apply_one(self, p, g, lr, master, wd):
        w32 = (master._data if master is not None else p._data).astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        if wd:
            g32 = g32 + wd * w32
        m = self._acc("moment", p, dtype=jnp.float32)
        u = self._acc("inf_norm", p, dtype=jnp.float32)
        t = self._step_count._data.astype(jnp.float32)
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        u._data = jnp.maximum(self._beta2 * u._data, jnp.abs(g32))
        lr32 = lr.astype(jnp.float32) / (1 - jnp.power(self._beta1, t))
        self._param_update(p, master,
                           w32 - lr32 * m._data / (u._data + self._eps))


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, p, g, lr, master, wd):
        w32 = (master._data if master is not None else p._data).astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        t = self._step_count._data.astype(jnp.float32)
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        v._data = self._beta2 * v._data + (1 - self._beta2) * jnp.square(g32)
        mhat = m._data / (1 - jnp.power(self._beta1, t))
        vhat = v._data / (1 - jnp.power(self._beta2, t))
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        if wd and not (self._exclude_fn and self._exclude_fn(p)):
            r = r + wd * w32
        w_norm = jnp.linalg.norm(w32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._param_update(p, master, w32 - lr.astype(jnp.float32) * trust * r)


class Adadelta(Optimizer):
    """reference python/paddle/optimizer/adadelta.py."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._eps = epsilon
        self._rho = rho

    def _apply_one(self, p, g, lr, master, wd):
        w = (master._data if master is not None else p._data).astype(jnp.float32)
        g = g.astype(jnp.float32)
        if wd:
            g = g + wd * w
        avg_sq = self._acc("_avg_squared_grad", p, dtype=jnp.float32)
        avg_up = self._acc("_avg_squared_update", p, dtype=jnp.float32)
        avg_sq._data = self._rho * avg_sq._data + (1 - self._rho) * g * g
        upd = (jnp.sqrt(avg_up._data + self._eps)
               / jnp.sqrt(avg_sq._data + self._eps)) * g
        avg_up._data = self._rho * avg_up._data + (1 - self._rho) * upd * upd
        self._param_update(p, master, w - lr.astype(jnp.float32) * upd)


class ASGD(Optimizer):
    """Averaged SGD (reference python/paddle/optimizer/asgd.py): keeps a
    running average of the last ``d`` gradients."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._n = max(int(batch_num), 1)

    def _apply_one(self, p, g, lr, master, wd):
        w = (master._data if master is not None else p._data).astype(jnp.float32)
        g = g.astype(jnp.float32)
        if wd:
            g = g + wd * w
        d = self._acc("_d", p, dtype=jnp.float32)       # sum of buffer
        # ring buffer of n grads is O(n·param) in the reference too; a
        # running mean over the last n via exponential window matches
        # its steady-state: d <- d - d/n + g
        d._data = d._data - d._data / self._n + g
        self._param_update(p, master,
                           w - lr.astype(jnp.float32) * d._data / self._n)


class Rprop(Optimizer):
    """Resilient backprop (reference python/paddle/optimizer/rprop.py):
    per-weight step sizes adapted by grad sign agreement."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_lo, self._lr_hi = learning_rate_range
        self._eta_neg, self._eta_pos = etas
        self._init_lr = float(learning_rate) if not isinstance(
            learning_rate, LRScheduler) else learning_rate()

    def _apply_one(self, p, g, lr, master, wd):
        w = (master._data if master is not None else p._data).astype(jnp.float32)
        g = g.astype(jnp.float32)
        prev = self._acc("_prev_grad", p, dtype=jnp.float32)
        steps = self._acc("_step_size", p,
                          init=jnp.full(p._data.shape, self._init_lr,
                                        jnp.float32))
        sign = jnp.sign(g * prev._data)
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        steps._data = jnp.clip(steps._data * factor, self._lr_lo, self._lr_hi)
        # on sign flip: do not step, zero the remembered grad
        g_eff = jnp.where(sign < 0, 0.0, g)
        prev._data = g_eff
        self._param_update(p, master, w - steps._data * jnp.sign(g_eff))


class NAdam(Adam):
    """Nesterov Adam (reference python/paddle/optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip,
                         multi_precision=multi_precision, name=name)
        self._psi = momentum_decay

    def _apply_one(self, p, g, lr, master, wd):
        w = (master._data if master is not None else p._data).astype(jnp.float32)
        g = g.astype(jnp.float32)
        if wd:
            g = g + wd * w
        t = self._step_count._data.astype(jnp.float32)
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_next = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        prod = self._acc("_mu_product", p,
                         init=jnp.ones((), jnp.float32))
        prod._data = prod._data * mu_t
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        m._data = self._beta1 * m._data + (1 - self._beta1) * g
        v._data = self._beta2 * v._data + (1 - self._beta2) * g * g
        mhat = (mu_next * m._data / (1 - prod._data * mu_next)
                + (1 - mu_t) * g / (1 - prod._data))
        vhat = v._data / (1 - jnp.power(self._beta2, t))
        self._param_update(
            p, master,
            w - lr.astype(jnp.float32) * mhat / (jnp.sqrt(vhat) + self._eps))


class RAdam(Adam):
    """Rectified Adam (reference python/paddle/optimizer/radam.py)."""

    def _apply_one(self, p, g, lr, master, wd):
        w = (master._data if master is not None else p._data).astype(jnp.float32)
        g = g.astype(jnp.float32)
        if wd:
            g = g + wd * w
        t = self._step_count._data.astype(jnp.float32)
        m = self._acc("moment1", p, dtype=jnp.float32)
        v = self._acc("moment2", p, dtype=jnp.float32)
        m._data = self._beta1 * m._data + (1 - self._beta1) * g
        v._data = self._beta2 * v._data + (1 - self._beta2) * g * g
        mhat = m._data / (1 - jnp.power(self._beta1, t))
        rho_inf = 2.0 / (1 - self._beta2) - 1
        rho_t = rho_inf - 2 * t * jnp.power(self._beta2, t) / (
            1 - jnp.power(self._beta2, t))
        lr32 = lr.astype(jnp.float32)
        # variance-rectified branch vs un-adapted (SGD-with-momentum)
        r = jnp.sqrt(jnp.maximum(
            (rho_t - 4) * (rho_t - 2) * rho_inf
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-12), 0.0))
        vhat = jnp.sqrt(v._data / (1 - jnp.power(self._beta2, t)))
        upd = jnp.where(rho_t > 5.0,
                        r * mhat / (vhat + self._eps),
                        mhat)
        self._param_update(p, master, w - lr32 * upd)

"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""
from . import lr
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adagrad,
                        RMSProp, Adamax, Lamb, L1Decay, L2Decay,
                        Adadelta, ASGD, Rprop, NAdam, RAdam)
from .lbfgs import LBFGS

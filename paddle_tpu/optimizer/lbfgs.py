"""L-BFGS optimizer with strong-Wolfe line search.

Reference: python/paddle/optimizer/lbfgs.py (class LBFGS, _strong_wolfe).
Redesigned, not translated: the reference walks per-parameter dense
tensors with its own flatten/offset bookkeeping; here the history and
direction math run on ONE flat f32 vector (ravel of all trainable
params), which XLA handles as a handful of fused vector ops — there is
no per-parameter kernel-launch cost to amortise on TPU. The closure
runs the user's eager forward+backward, so this composes with the tape
(autograd/tape.py) exactly like the reference's dygraph LBFGS.

Like the reference, ``step(closure)`` may evaluate the closure several
times (line search); state (history, Hessian-diagonal estimate) lives
on the optimizer and is checkpointable via ``state_dict``.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..autograd import no_grad
from .optimizer import Optimizer


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2); reference
    lbfgs.py _cubic_interpolate."""
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 ** 2 - g1 * g2
    if d2_square >= 0:
        d2 = d2_square ** 0.5
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(min_pos, xmin_bound), xmax_bound)
    return (xmin_bound + xmax_bound) / 2.0


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference python/paddle/optimizer/lbfgs.py).

    Usage (paddle UX)::

        opt = LBFGS(parameters=model.parameters(), line_search_fn="strong_wolfe")
        def closure():
            opt.clear_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            return loss
        loss = opt.step(closure)
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn: Optional[str] = None,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("only line_search_fn='strong_wolfe' is "
                             f"supported, got {line_search_fn!r}")
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        # flat-vector history (host numpy: tiny, control-flow heavy)
        self._old_dirs: List[np.ndarray] = []
        self._old_stps: List[np.ndarray] = []
        self._ro: List[float] = []
        self._H_diag = 1.0
        self._prev_flat_grad: Optional[np.ndarray] = None
        self._d: Optional[np.ndarray] = None
        self._t = 0.0
        self._n_iter = 0

    # -- flat <-> params ----------------------------------------------------
    def _trainable(self):
        return [p for p in self._param_list if not p.stop_gradient]

    def _gather_flat_grad(self) -> np.ndarray:
        params_grads = [
            (p, p._grad._data if p._grad is not None
             else jnp.zeros_like(p._data)) for p in self._trainable()]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        parts = []
        for p, g in params_grads:
            if self._weight_decay is not None:
                g = g + self._decay_coeff(p) * p._data.astype(g.dtype)
            parts.append(np.asarray(g, np.float64).ravel())
        return np.concatenate(parts)

    def _gather_flat_param(self) -> np.ndarray:
        return np.concatenate(
            [np.asarray(p._data, np.float64).ravel() for p in self._trainable()])

    @no_grad()
    def _set_flat_param(self, flat: np.ndarray):
        off = 0
        for p in self._trainable():
            n = int(np.prod(p._data.shape)) if p._data.ndim else 1
            chunk = flat[off:off + n].reshape(p._data.shape)
            p._data = jnp.asarray(chunk, p._data.dtype)
            off += n

    # -- strong wolfe (reference lbfgs.py _strong_wolfe) --------------------
    def _directional_evaluate(self, closure, x0, t, d):
        self._set_flat_param(x0 + t * d)
        loss = float(closure())
        g = self._gather_flat_grad()
        return loss, g

    def _strong_wolfe(self, closure, x0, t, d, f, g, gtd,
                      c1=1e-4, c2=0.9, max_ls=25):
        d_norm = float(np.abs(d).max())
        g = g.copy()
        f_new, g_new = self._directional_evaluate(closure, x0, t, d)
        ls_func_evals = 1
        gtd_new = float(g_new @ d)

        # bracket phase
        t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
        done = False
        ls_iter = 0
        while ls_iter < max_ls:
            if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new.copy()]
                bracket_gtd = [gtd_prev, gtd_new]
                break
            if abs(gtd_new) <= -c2 * gtd:
                bracket = [t, t]
                bracket_f = [f_new, f_new]
                bracket_g = [g_new, g_new]
                done = True
                break
            if gtd_new >= 0:
                bracket = [t_prev, t]
                bracket_f = [f_prev, f_new]
                bracket_g = [g_prev, g_new.copy()]
                bracket_gtd = [gtd_prev, gtd_new]
                break
            min_step = t + 0.01 * (t - t_prev)
            max_step = t * 10
            tmp = t
            t = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new,
                                   gtd_new, bounds=(min_step, max_step))
            t_prev, f_prev, g_prev, gtd_prev = tmp, f_new, g_new.copy(), gtd_new
            f_new, g_new = self._directional_evaluate(closure, x0, t, d)
            ls_func_evals += 1
            gtd_new = float(g_new @ d)
            ls_iter += 1
        else:
            bracket = [0.0, t]
            bracket_f = [f, f_new]
            bracket_g = [g, g_new]
            bracket_gtd = [gtd, gtd_new]

        # zoom phase
        insuf_progress = False
        low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[-1] else (1, 0)
        while not done and ls_iter < max_ls:
            if abs(bracket[1] - bracket[0]) * d_norm < self.tolerance_change:
                break
            t = _cubic_interpolate(bracket[0], bracket_f[0], bracket_gtd[0],
                                   bracket[1], bracket_f[1], bracket_gtd[1])
            eps = 0.1 * abs(bracket[1] - bracket[0])
            if min(max(bracket) - t, t - min(bracket)) < eps:
                if insuf_progress or t >= max(bracket) or t <= min(bracket):
                    t = (max(bracket) - eps if abs(t - max(bracket))
                         < abs(t - min(bracket)) else min(bracket) + eps)
                    insuf_progress = False
                else:
                    insuf_progress = True
            else:
                insuf_progress = False
            f_new, g_new = self._directional_evaluate(closure, x0, t, d)
            ls_func_evals += 1
            gtd_new = float(g_new @ d)
            ls_iter += 1
            if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[low_pos]:
                bracket[high_pos] = t
                bracket_f[high_pos] = f_new
                bracket_g[high_pos] = g_new.copy()
                bracket_gtd[high_pos] = gtd_new
                low_pos, high_pos = ((0, 1) if bracket_f[0] <= bracket_f[1]
                                     else (1, 0))
            else:
                if abs(gtd_new) <= -c2 * gtd:
                    done = True
                elif gtd_new * (bracket[high_pos] - bracket[low_pos]) >= 0:
                    bracket[high_pos] = bracket[low_pos]
                    bracket_f[high_pos] = bracket_f[low_pos]
                    bracket_g[high_pos] = bracket_g[low_pos]
                    bracket_gtd[high_pos] = bracket_gtd[low_pos]
                bracket[low_pos] = t
                bracket_f[low_pos] = f_new
                bracket_g[low_pos] = g_new.copy()
                bracket_gtd[low_pos] = gtd_new

        t = bracket[low_pos]
        f_new = bracket_f[low_pos]
        g_new = bracket_g[low_pos]
        return f_new, g_new, t, ls_func_evals

    # -- step ---------------------------------------------------------------
    def step(self, closure: Callable[[], "Tensor"] = None):
        """One LBFGS iteration group (up to ``max_iter`` inner updates).
        ``closure`` must clear grads, compute the loss, call backward, and
        return the loss — it will be called multiple times."""
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        self._sync_lr()
        lr = float(np.asarray(self._lr._data))

        orig_loss = closure()
        loss = float(orig_loss)
        current_evals = 1
        flat_grad = self._gather_flat_grad()
        if float(np.abs(flat_grad).max()) <= self.tolerance_grad:
            return orig_loss

        n_iter = 0
        while n_iter < self.max_iter:
            n_iter += 1
            self._n_iter += 1
            if self._n_iter == 1:
                self._d = -flat_grad
                self._H_diag = 1.0
            else:
                y = flat_grad - self._prev_flat_grad
                s = self._d * self._t
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(self._old_dirs) == self.history_size:
                        self._old_dirs.pop(0)
                        self._old_stps.pop(0)
                        self._ro.pop(0)
                    self._old_dirs.append(y)
                    self._old_stps.append(s)
                    self._ro.append(1.0 / ys)
                    self._H_diag = ys / float(y @ y)
                # two-loop recursion
                num_old = len(self._old_dirs)
                al = [0.0] * num_old
                q = -flat_grad
                for i in range(num_old - 1, -1, -1):
                    al[i] = float(self._old_stps[i] @ q) * self._ro[i]
                    q = q - al[i] * self._old_dirs[i]
                d = q * self._H_diag
                for i in range(num_old):
                    be_i = float(self._old_dirs[i] @ d) * self._ro[i]
                    d = d + self._old_stps[i] * (al[i] - be_i)
                self._d = d
            self._prev_flat_grad = flat_grad.copy()
            prev_loss = loss

            # -- step length
            if self._n_iter == 1:
                self._t = min(1.0, 1.0 / float(np.abs(flat_grad).sum())) * lr
            else:
                self._t = lr
            gtd = float(flat_grad @ self._d)
            if gtd > -self.tolerance_change:
                break
            if self.line_search_fn == "strong_wolfe":
                x0 = self._gather_flat_param()
                loss, flat_grad, self._t, ls_evals = self._strong_wolfe(
                    closure, x0, self._t, self._d, loss, flat_grad, gtd)
                self._set_flat_param(x0 + self._t * self._d)
                current_evals += ls_evals
            else:
                self._set_flat_param(
                    self._gather_flat_param() + self._t * self._d)
                if n_iter != self.max_iter:
                    loss = float(closure())
                    flat_grad = self._gather_flat_grad()
                    current_evals += 1

            # -- convergence checks
            if current_evals >= self.max_eval:
                break
            if float(np.abs(flat_grad).max()) <= self.tolerance_grad:
                break
            if float(np.abs(self._d * self._t).max()) <= self.tolerance_change:
                break
            if abs(loss - prev_loss) < self.tolerance_change:
                break

        self._step_count._data = self._step_count._data + 1
        return orig_loss

    def state_dict(self):
        out = super().state_dict()
        out.update({
            "old_dirs": [np.asarray(a) for a in self._old_dirs],
            "old_stps": [np.asarray(a) for a in self._old_stps],
            "ro": list(self._ro),
            "H_diag": self._H_diag,
            "prev_flat_grad": (None if self._prev_flat_grad is None
                               else np.asarray(self._prev_flat_grad)),
            "d": None if self._d is None else np.asarray(self._d),
            "t": self._t,
            "n_iter": self._n_iter,
        })
        return out

    def set_state_dict(self, state):
        super().set_state_dict(state)
        self._old_dirs = [np.asarray(a) for a in state.get("old_dirs", [])]
        self._old_stps = [np.asarray(a) for a in state.get("old_stps", [])]
        self._ro = list(state.get("ro", []))
        self._H_diag = state.get("H_diag", 1.0)
        pfg = state.get("prev_flat_grad")
        self._prev_flat_grad = None if pfg is None else np.asarray(pfg)
        d = state.get("d")
        self._d = None if d is None else np.asarray(d)
        self._t = state.get("t", 0.0)
        self._n_iter = state.get("n_iter", 0)

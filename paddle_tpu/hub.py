"""paddle.hub — load models from a hubconf.py entrypoint file.

Reference: python/paddle/hub.py (list/help/load with github/gitee/local
sources). The TPU build environment has zero egress, so remote sources
raise with guidance; the local protocol (a directory containing
``hubconf.py`` whose public callables are entrypoints) is fully
supported — which is also what the reference uses once a repo is
cached.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop("paddle_tpu_hubconf", None)
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str):
    if source not in ("local",):
        raise NotImplementedError(
            f"hub source {source!r} needs network egress; clone the repo "
            "and use source='local' with repo_dir pointing at it")


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:  # noqa: A001 (reference API name)
    """Names of entrypoints exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"{model!r} not in {repo_dir}/hubconf.py "
                         f"(has: {list(repo_dir)})")
    return getattr(mod, model).__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    """Instantiate entrypoint ``model`` from the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"{model!r} not in {repo_dir}/hubconf.py "
                         f"(has: {list(repo_dir)})")
    return getattr(mod, model)(**kwargs)

"""paddle_tpu.profiler — host+device tracing.

Reference: python/paddle/profiler/profiler.py:358 (Profiler with scheduler
windows, export:853) over the C++ RecordEvent/HostTracer/CudaTracer stack
(paddle/fluid/platform/profiler/).

TPU-native: device-side tracing is jax.profiler (XPlane -> TensorBoard /
Perfetto); the RecordEvent python annotation API is kept and forwards to
jax.profiler.TraceAnnotation so user marks appear inside the device trace.
Host-side spans are also timed in-process for the summary table.
"""
from __future__ import annotations

import contextlib
import enum
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Iterable, Optional

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Reference make_scheduler: step -> state windows."""
    period = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready callback exporting to ``dir_name``
    (jax writes xplane/trace-viewer files there)."""
    def handler(prof):
        prof._export_dir = dir_name
    return handler


_records = threading.local()
_stats_lock = threading.Lock()
_host_stats = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]

# span sinks: callables (name, t0_s, t1_s) invoked as each RecordEvent
# span closes, timestamps on time.monotonic(). The observability span
# tracer bridges through here (observability.bridge_record_events) so
# RecordEvent annotations land in Perfetto exports next to the serving
# engine's own spans. Sink errors are swallowed — a broken exporter
# must not take down the annotated hot path.
_span_sinks = []


def add_span_sink(fn) -> None:
    """Register ``fn(name, t0_s, t1_s)`` for every closing RecordEvent
    span (monotonic-clock seconds)."""
    _span_sinks.append(fn)


def remove_span_sink(fn) -> None:
    try:
        _span_sinks.remove(fn)
    except ValueError:
        pass


class RecordEvent:
    """User annotation span (reference: paddle.profiler.RecordEvent /
    C++ platform::RecordEvent). Times the host span and nests a
    jax.profiler.TraceAnnotation so the mark shows up on device traces."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        if self._ann is not None:
            dt = time.perf_counter() - self._t0
            # sample the monotonic endpoint NEXT to dt, before the
            # locked stats update / annotation teardown, so bridged
            # spans are not translated late under lock contention
            t1 = time.monotonic()
            with _stats_lock:
                st = _host_stats[self.name]
                st[0] += 1
                st[1] += dt
            self._ann.__exit__(None, None, None)
            self._ann = None
            if _span_sinks:
                for fn in list(_span_sinks):
                    try:
                        fn(self.name, t1 - dt, t1)
                    except Exception:
                        pass

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def host_statistics():
    """name -> {calls, total_ms, avg_ms} for RecordEvent spans."""
    with _stats_lock:
        return {k: {"calls": v[0], "total_ms": v[1] * 1e3,
                    "avg_ms": v[1] * 1e3 / max(v[0], 1)}
                for k, v in _host_stats.items()}


def reset_host_statistics():
    with _stats_lock:
        _host_stats.clear()


class Profiler:
    """paddle.profiler.Profiler-compatible facade over jax.profiler.

    with Profiler(scheduler=(2, 5)) as p:
        for batch in loader:
            step(batch)
            p.step()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, record_shapes=False,
                 profile_memory=False, timer_only: bool = False,
                 emit_nvtx: bool = False, with_flops: bool = False):
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=start, ready=0,
                                       record=end - start, repeat=1)
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._export_dir = os.environ.get(
            "PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._step_times = []
        self._last_step_t = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._last_step_t = time.perf_counter()
        self._transition()
        return self

    def stop(self):
        if self._tracing:
            self._stop_trace()
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        self._transition()

    def _transition(self):
        state = (self._scheduler(self._step) if self._scheduler
                 else ProfilerState.RECORD)
        if self._timer_only:
            return
        should_trace = state in (ProfilerState.RECORD,
                                 ProfilerState.RECORD_AND_RETURN)
        if should_trace and not self._tracing:
            self._start_trace()
        elif not should_trace and self._tracing:
            self._stop_trace()
        self._state = state

    def _start_trace(self):
        try:
            jax.profiler.start_trace(self._export_dir)
            self._tracing = True
        except Exception:
            self._tracing = False  # e.g. trace already active

    def _stop_trace(self):
        try:
            jax.profiler.stop_trace()
        finally:
            self._tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reports ------------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = ["Profiler summary", "-" * 60]
        if self._step_times:
            ts = self._step_times
            lines.append(
                f"steps: {len(ts)}  avg {1e3 * sum(ts) / len(ts):.2f} ms  "
                f"min {1e3 * min(ts):.2f}  max {1e3 * max(ts):.2f}")
        for name, st in sorted(host_statistics().items(),
                               key=lambda kv: -kv[1]["total_ms"]):
            lines.append(f"{name:<40} x{st['calls']:<6} "
                         f"total {st['total_ms']:.2f} ms  "
                         f"avg {st['avg_ms']:.3f} ms")
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path: str, format: str = "json"):
        """Traces are written by stop_trace to the profile dir; this
        records the requested destination for tooling parity."""
        self._export_dir = path


@contextlib.contextmanager
def profile(**kw):
    p = Profiler(**kw)
    p.start()
    try:
        yield p
    finally:
        p.stop()

"""Version compatibility shims for the jax API surface we ride.

One place adapts the repo to the installed jax:

  - ``shard_map``: top-level ``jax.shard_map`` (new) vs
    ``jax.experimental.shard_map.shard_map`` (<= 0.4.x), whose
    replication-check kwarg is ``check_vma`` vs ``check_rep``. Callers
    use the NEW spelling; this wrapper translates downward.
"""
from __future__ import annotations

try:
    from jax import shard_map as _shard_map_impl
    _KWARG = "check_vma"
except ImportError:  # pre-0.5 jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _KWARG = "check_rep"

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    if check_vma is not None:
        kw[_KWARG] = check_vma
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)

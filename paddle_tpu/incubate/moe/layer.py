"""MoELayer — expert-parallel mixture of experts module.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(MoELayer: gate -> global_scatter -> experts -> global_gather -> combine).

TPU-native: experts are ONE stacked weight pytree with a leading E axis
sharded over the mesh ``ep`` axis; dispatch/combine are dense einsums
(functional.py) and GSPMD inserts the all_to_all. The layer also works
unsharded (single device) with identical numerics.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from ...nn.layer import Layer
from ...nn import initializer as I
from ...parallel.mesh import get_hybrid_mesh
from . import functional as MF
from .gate import NaiveGate, GShardGate, SwitchGate


class ExpertLayer(Layer):
    """A single expert FFN (moe_layer.py ExpertLayer): SwiGLU d->f->d."""

    def __init__(self, d_model: int, d_hidden: int):
        super().__init__()
        self.w_gate = self.create_parameter(
            (d_model, d_hidden), default_initializer=I.XavierUniform())
        self.w_up = self.create_parameter(
            (d_model, d_hidden), default_initializer=I.XavierUniform())
        self.w_down = self.create_parameter(
            (d_hidden, d_model), default_initializer=I.XavierUniform())

    def forward(self, x):
        h = jax.nn.silu(x @ self.w_gate.data) * (x @ self.w_up.data)
        return h @ self.w_down.data


class MoELayer(Layer):
    """Mixture of experts over a list of ExpertLayers.

    Args mirror moe_layer.py: ``gate`` is a config dict
    ({"type": "gshard"|"switch"|"naive", "top_k": k}) or a gate Layer;
    ``experts`` a list of ExpertLayer. ``moe_group``/``mp_group`` are
    accepted for API parity; placement actually comes from the global
    HybridMesh's ep axis.
    """

    def __init__(self, d_model: int, experts: Optional[List[Layer]] = None,
                 gate=None, moe_group=None, mp_group=None,
                 recompute_interval: int = 0, num_expert: Optional[int] = None,
                 d_hidden: Optional[int] = None,
                 capacity_factor: float = 2.0):
        super().__init__()
        if experts is None:
            assert num_expert and d_hidden, \
                "pass experts=[...] or num_expert+d_hidden"
            experts = [ExpertLayer(d_model, d_hidden)
                       for _ in range(num_expert)]
        self.experts = experts
        for i, e in enumerate(experts):
            self.add_sublayer(f"expert_{i}", e)
        self.num_expert = len(experts)
        self.capacity_factor = capacity_factor

        if gate is None or isinstance(gate, dict):
            cfg = dict(gate or {})
            kind = cfg.get("type", "gshard")
            top_k = cfg.get("top_k", 2)
            cls = {"gshard": GShardGate, "switch": SwitchGate,
                   "naive": NaiveGate}[kind]
            gate = cls(d_model, self.num_expert, topk=top_k)
        self.gate = gate
        self.add_sublayer("gate", self.gate)

    def _stacked(self, name: str) -> jax.Array:
        ws = jnp.stack([getattr(e, name).data for e in self.experts])
        hm = get_hybrid_mesh()
        if hm is not None and hm.ep_degree > 1:
            ws = jax.lax.with_sharding_constraint(
                ws, hm.sharding("ep", *([None] * (ws.ndim - 1))))
        return ws

    def forward(self, x, key: Optional[jax.Array] = None):
        data = x.data if hasattr(x, "data") else x
        hm = get_hybrid_mesh()
        ep_axis = "ep" if (hm is not None and hm.ep_degree > 1) else None
        # route through the gate module so its policy (gshard random
        # second-expert routing, switch jitter) actually applies
        dispatch, combine, aux = self.gate(
            data, capacity_factor=self.capacity_factor, key=key)
        xs = data.reshape(-1, data.shape[-1])
        y = MF.moe_expert_compute(
            xs, dispatch, combine,
            self._stacked("w_gate"), self._stacked("w_up"),
            self._stacked("w_down"), ep_axis=ep_axis)
        self.l_aux = aux
        return y.reshape(data.shape)

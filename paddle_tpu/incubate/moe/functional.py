"""Functional MoE: gating + expert dispatch, TPU-first.

Reference capability: python/paddle/incubate/distributed/models/moe/
(moe_layer.py:119-190,263 — gates + global_scatter/global_gather alltoall
dispatch; gshard_gate.py, switch_gate.py, naive_gate.py) and the fused
cutlass MoE kernel (paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu).

TPU-native redesign: instead of per-rank index scatter + NCCL alltoall, the
whole dispatch is expressed as dense one-hot einsums over static shapes
(the GShard formulation). Expert weights carry a leading E axis sharded over
the mesh's ``ep`` axis; when dispatch/combine einsums contract against
ep-sharded operands, XLA GSPMD emits exactly the all_to_all the reference
hand-codes — and the expert FFN itself is one big grouped batched matmul
on the MXU.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def default_capacity(num_tokens: int, num_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    """Per-expert token slots C (gshard_gate.py capacity computation)."""
    cap = int(capacity_factor * top_k * num_tokens / num_experts)
    return max(cap, top_k)


def top_k_gating(
    logits: jax.Array,
    top_k: int,
    capacity: int,
    *,
    key: Optional[jax.Array] = None,
    second_policy: str = "all",
    normalize_topk: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Dense top-k gating (GShard).

    Args:
      logits: ``[S, E]`` router logits for S tokens over E experts.
      top_k: experts per token (1 = switch, 2 = gshard).
      capacity: per-expert slot count C; overflow tokens are dropped.
      key: optional PRNG key; with ``second_policy='random'`` the 2nd+
        expert is kept with probability proportional to its gate value
        (gshard_gate.py random routing).

    Returns:
      (dispatch, combine, aux_loss) with dispatch ``[S, E, C]`` one-hot,
      combine ``[S, E, C]`` float weights, and the load-balance aux loss
      (switch/gshard l_aux: E * mean_e(importance_e * load_e)).
    """
    S, E = logits.shape
    compute_dtype = jnp.float32
    raw_gates = jax.nn.softmax(logits.astype(compute_dtype), axis=-1)

    # iteratively peel off the top-k experts per token
    masks, gate_vals = [], []
    g = raw_gates
    for i in range(top_k):
        idx = jnp.argmax(g, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=compute_dtype)      # [S, E]
        g = g * (1.0 - m)  # peel BEFORE random drop so a dropped expert
        #                    is never re-picked at the next iteration
        gv = jnp.sum(raw_gates * m, axis=-1)                 # [S]
        if i > 0 and second_policy == "random" and key is not None:
            # keep the i-th expert with prob 2*gate (gshard random routing)
            key, sub = jax.random.split(key)
            keep = jax.random.uniform(sub, (S,)) < (2.0 * gv)
            m = m * keep[:, None].astype(compute_dtype)
            gv = gv * keep.astype(compute_dtype)
        masks.append(m)
        gate_vals.append(gv)

    # aux load-balance loss uses the top-1 assignment (switch_gate.py)
    density = jnp.mean(masks[0], axis=0)                     # fraction routed
    density_proxy = jnp.mean(raw_gates, axis=0)              # mean gate prob
    aux_loss = jnp.mean(density * density_proxy) * (E * E)

    # position of each token in its expert's queue; earlier k-slots and
    # earlier tokens win capacity (cumsum ordering == reference prioritizing)
    dispatch = jnp.zeros((S, E, capacity), compute_dtype)
    combine = jnp.zeros((S, E, capacity), compute_dtype)
    if normalize_topk:  # mixtral-style renormalization over the chosen k
        denom = sum(gate_vals)
        denom = jnp.where(denom > 0, denom, 1.0)
        gate_vals = [gv / denom for gv in gate_vals]
    running = jnp.zeros((E,), compute_dtype)
    for m, gv in zip(masks, gate_vals):
        pos_all = jnp.cumsum(m, axis=0) - m + running        # [S, E]
        pos = jnp.sum(pos_all * m, axis=-1).astype(jnp.int32)  # [S]
        running = running + jnp.sum(m, axis=0)
        within = (pos < capacity).astype(compute_dtype)
        oh_pos = jax.nn.one_hot(pos, capacity, dtype=compute_dtype)  # [S, C]
        d = (m * within[:, None])[:, :, None] * oh_pos[:, None, :]   # [S,E,C]
        dispatch = dispatch + d
        combine = combine + gv[:, None, None] * d
    return dispatch, combine, aux_loss


def moe_ffn_dropless(
    x: jax.Array,
    gate_w: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    top_k: int = 2,
):
    """Dropless token-choice MoE FFN (same contract as :func:`moe_ffn`,
    returns ``(y, aux_loss)``): routes through the authored grouped-GEMM
    Pallas kernel (ops/pallas/grouped_matmul.py) — no capacity factor,
    nothing dropped. Single-device/dp layouts; EP all_to_all dispatch
    stays on :func:`moe_ffn`. The load-balance aux loss uses the SAME
    switch-gate spelling as :func:`top_k_gating` so the two paths cannot
    drift."""
    from ...ops.pallas.grouped_matmul import moe_mlp_dropless

    orig_shape = x.shape
    D = orig_shape[-1]
    E = w_gate.shape[0]
    xs = x.reshape(-1, D)
    logits = xs.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    raw_gates = jax.nn.softmax(logits, axis=-1)
    cw, eids = jax.lax.top_k(raw_gates, top_k)
    # aux: identical formula to top_k_gating (top-1 density x mean prob)
    density = jnp.mean(jax.nn.one_hot(eids[:, 0], E, dtype=jnp.float32),
                       axis=0)
    density_proxy = jnp.mean(raw_gates, axis=0)
    aux = jnp.mean(density * density_proxy) * (E * E)
    y = moe_mlp_dropless(xs, eids, cw.astype(x.dtype), w_gate, w_up,
                         w_down)
    return y.reshape(orig_shape), aux


def moe_expert_compute(
    xs: jax.Array,
    dispatch: jax.Array,
    combine: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    ep_axis: Optional[str] = None,
    activation=jax.nn.silu,
) -> jax.Array:
    """Dispatch -> grouped expert SwiGLU -> combine, on tokens ``[S, D]``
    with gating tensors ``[S, E, C]`` (shared by moe_ffn and MoELayer)."""
    dispatch = dispatch.astype(xs.dtype)
    combine = combine.astype(xs.dtype)
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, xs)      # [E, C, D]
    if ep_axis is not None:
        expert_in = lax.with_sharding_constraint(
            expert_in, jax.sharding.PartitionSpec(ep_axis, None, None))
    h = activation(jnp.einsum("ecd,edf->ecf", expert_in, w_gate))
    h = h * jnp.einsum("ecd,edf->ecf", expert_in, w_up)
    expert_out = jnp.einsum("ecf,efd->ecd", h, w_down)       # [E, C, D]
    if ep_axis is not None:
        expert_out = lax.with_sharding_constraint(
            expert_out, jax.sharding.PartitionSpec(ep_axis, None, None))
    return jnp.einsum("sec,ecd->sd", combine, expert_out)    # [S, D]


def moe_ffn(
    x: jax.Array,
    gate_w: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    top_k: int = 2,
    capacity_factor: float = 2.0,
    key: Optional[jax.Array] = None,
    ep_axis: Optional[str] = None,
    activation=jax.nn.silu,
) -> Tuple[jax.Array, jax.Array]:
    """Mixture-of-experts SwiGLU FFN over tokens ``x`` ``[..., D]``.

    Expert weights are stacked on a leading E axis: ``w_gate/w_up [E, D, F]``,
    ``w_down [E, F, D]``. With ``ep_axis`` set and the weights ep-sharded,
    the dispatch/combine einsums below compile to the expert-parallel
    all_to_all (moe_layer.py global_scatter/global_gather equivalent).

    Returns (y, aux_loss) with y shaped like x.
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    E = w_gate.shape[0]
    xs = x.reshape(-1, D)                                    # [S, D]
    S = xs.shape[0]
    capacity = default_capacity(S, E, top_k, capacity_factor)

    logits = xs.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # [S, E]
    dispatch, combine, aux = top_k_gating(logits, top_k, capacity, key=key)
    y = moe_expert_compute(xs, dispatch, combine, w_gate, w_up, w_down,
                           ep_axis=ep_axis, activation=activation)
    return y.reshape(orig_shape), aux.astype(jnp.float32)

from .functional import moe_ffn, top_k_gating, default_capacity
from .gate import NaiveGate, GShardGate, SwitchGate
from .layer import MoELayer, ExpertLayer

__all__ = [
    "moe_ffn", "top_k_gating", "default_capacity",
    "NaiveGate", "GShardGate", "SwitchGate", "MoELayer", "ExpertLayer",
]

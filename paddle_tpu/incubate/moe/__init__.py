from .functional import moe_ffn, top_k_gating, default_capacity
from .gate import NaiveGate, GShardGate, SwitchGate
from .layer import MoELayer, ExpertLayer

__all__ = [
    "moe_ffn", "top_k_gating", "default_capacity", "moe_mlp_dropless",
    "NaiveGate", "GShardGate", "SwitchGate", "MoELayer", "ExpertLayer",
]


def __getattr__(name):
    # dropless token-choice MoE over the authored Pallas grouped-matmul
    # kernel (fused_moe_kernel.cu counterpart) — imported lazily so the
    # einsum capacity path keeps working on installs where
    # jax.experimental.pallas is unavailable
    if name == "moe_mlp_dropless":
        from ...ops.pallas.grouped_matmul import moe_mlp_dropless
        return moe_mlp_dropless
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Gate modules for MoELayer.

Reference: python/paddle/incubate/distributed/models/moe/gate/
(naive_gate.py, gshard_gate.py, switch_gate.py). Each gate maps token
activations to (dispatch, combine, aux_loss) via the dense formulation in
``functional.top_k_gating``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...nn.layer import Layer
from ...nn import initializer as I
from . import functional as MF


class NaiveGate(Layer):
    """Plain learned top-k router, no randomness (naive_gate.py)."""

    top_k = 2
    second_policy = "all"

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__()
        self.num_expert = num_expert * world_size
        self.top_k = topk
        self.weight = self.create_parameter(
            (d_model, self.num_expert),
            default_initializer=I.XavierUniform())

    def forward(self, x, capacity_factor: float = 2.0,
                key: Optional[jax.Array] = None):
        xs = x.reshape(-1, x.shape[-1])
        logits = xs.astype(jnp.float32) @ self.weight.data.astype(jnp.float32)
        cap = MF.default_capacity(xs.shape[0], self.num_expert, self.top_k,
                                  capacity_factor)
        return MF.top_k_gating(logits, self.top_k, cap, key=key,
                               second_policy=self.second_policy)


class GShardGate(NaiveGate):
    """Top-2 with random second-expert routing (gshard_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = capacity
        self.second_policy = "random"


class SwitchGate(NaiveGate):
    """Top-1 switch-transformer router (switch_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, switch_eps: float = 0.1, capacity=(1.2, 2.4),
                 group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps

    def forward(self, x, capacity_factor: float = 2.0,
                key: Optional[jax.Array] = None):
        if key is not None:
            # switch jitter: multiplicative uniform noise on the logits
            noise = jax.random.uniform(
                key, x.shape, minval=1.0 - self.switch_eps,
                maxval=1.0 + self.switch_eps)
            x = x * noise.astype(x.dtype)
        return super().forward(x, capacity_factor, key=None)

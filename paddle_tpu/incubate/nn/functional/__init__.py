"""Fused functional ops (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, swiglu, fused_rotary_position_embedding, fused_moe, ...).

On TPU "fused" means: express the math in one traced region and let XLA's
fusion pass emit a single kernel — plus Pallas for the cases XLA can't fuse
(flash attention, ops/pallas/). The APIs keep the reference's names so
model code ports unchanged.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-5,
                   begin_norm_axis: int = -1, **kw):
    """fused_rms_norm.py equivalent; XLA fuses the whole thing."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=begin_norm_axis, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon) * norm_weight.astype(jnp.float32)
    if norm_bias is not None:
        out = out + norm_bias.astype(jnp.float32)
    return out.astype(dt)


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-5,
                     begin_norm_axis: int = -1, **kw):
    dt = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=begin_norm_axis, keepdims=True)
    var = jnp.var(xf, axis=begin_norm_axis, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if norm_weight is not None:
        out = out * norm_weight.astype(jnp.float32)
    if norm_bias is not None:
        out = out + norm_bias.astype(jnp.float32)
    return out.astype(dt)


def swiglu(x, y=None):
    """swiglu.py: silu(x) * y; single-arg form splits x in half."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def fused_bias_act(x, bias=None, act_method: str = "gelu", **kw):
    if bias is not None:
        x = x + bias
    return {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "silu": jax.nn.silu, "swiglu": swiglu}[act_method](x)


def fused_linear(x, weight, bias=None, transpose_weight: bool = False):
    if transpose_weight:
        weight = weight.T
    out = x @ weight
    return out + bias if bias is not None else out


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation: str = "gelu"):
    if trans_x:
        x = x.T
    if trans_y:
        y = y.T
    return fused_bias_act(x @ y, bias, act_method=activation)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    rotary_emb_base: float = 10000.0):
    """fused_rotary_position_embedding equivalent on [B, T, H, Dh] tensors."""
    B, T, _, Dh = q.shape
    if cos is None or sin is None:
        half = Dh // 2
        inv = 1.0 / (rotary_emb_base **
                     (jnp.arange(0, half, dtype=jnp.float32) / half))
        pos = (position_ids if position_ids is not None
               else jnp.broadcast_to(jnp.arange(T), (B, T)))
        ang = pos[..., None].astype(jnp.float32) * inv       # [B, T, half]
        cos, sin = jnp.cos(ang), jnp.sin(ang)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]

    def rot(x):
        if x is None:
            return None
        half = x.shape[-1] // 2
        if use_neox_rotary_style:
            x1, x2 = x[..., :half], x[..., half:]
            out = jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        else:
            x1, x2 = x[..., 0::2], x[..., 1::2]
            r1 = x1 * cos - x2 * sin
            r2 = x2 * cos + x1 * sin
            out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
        return out.astype(x.dtype)

    return rot(q), rot(k), rot(v)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, *, top_k: int = 2,
              capacity_factor: float = 2.0, **kw):
    """cutlass fused_moe_kernel.cu equivalent: dense-dispatch grouped GEMM
    (see incubate.moe.functional.moe_ffn). ffn1 [E, D, 2F] packs gate|up."""
    from ...moe.functional import moe_ffn
    w_gate, w_up = jnp.split(ffn1_weight, 2, axis=-1)
    y, _ = moe_ffn(x, gate_weight, w_gate, w_up, ffn2_weight,
                   top_k=top_k, capacity_factor=capacity_factor)
    return y


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               seq_len: int = 1, **kw):
    """One-token decode attention over a KV cache (reference
    masked_multihead_attention.py over
    masked_multihead_attention_kernel.cu).

    x: ``[B, 3*H*Dh]`` fused qkv for the CURRENT token. cache_kv:
    ``[2, B, H, S_max, Dh]``. sequence_lengths: ``[B]`` or ``[B, 1]``
    int — the position the new token occupies (and the number of valid
    cached keys before it); defaults to ``seq_len - 1`` for every row.
    bias: ``[3, H, Dh]`` qkv bias. src_mask: additive mask broadcast to
    ``[B, 1, 1, S_max]``. Returns ``(out [B, H*Dh], cache_kv_out)`` —
    cache semantics are FUNCTIONAL (a new array), not in-place like the
    CUDA op; quant/beam arguments are not supported.
    """
    from ....core.tensor import Tensor

    def arr(v):
        return v.data if isinstance(v, Tensor) else jnp.asarray(v)

    if cache_kv is None:
        raise ValueError("masked_multihead_attention needs cache_kv "
                         "[2, B, H, S_max, Dh]")
    if beam_cache_offset is not None:
        raise NotImplementedError(
            "beam search cache offsets are not supported; use the "
            "models/llama.py generate path for batched decoding")
    if rotary_tensor is not None or cum_offsets is not None:
        raise NotImplementedError(
            "rotary_tensor/cum_offsets are not supported: apply rope to "
            "the qkv BEFORE this op (fused_rotary_position_embedding) — "
            "silently skipping the rotation would corrupt decode numerics")
    quant = {k: v for k, v in kw.items()
             if k in ("qkv_out_scale", "out_shift", "out_smooth")
             and v is not None}
    if quant or kw.get("out_scale", -1) not in (-1, None):
        raise NotImplementedError(
            f"quantized decode ({sorted(quant) or 'out_scale'}) is not "
            "supported; see paddle_tpu.quantization for PTQ/QAT")
    xv = arr(x)
    ck = arr(cache_kv)
    _, B, H, S, Dh = ck.shape
    qkv = xv.reshape(B, 3, H, Dh)
    if bias is not None:
        qkv = qkv + arr(bias).reshape(1, 3, H, Dh).astype(qkv.dtype)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]       # [B, H, Dh]
    if sequence_lengths is None:
        pos = jnp.full((B,), seq_len - 1, jnp.int32)
    else:
        pos = arr(sequence_lengths).reshape(B).astype(jnp.int32)

    # scatter the new k/v into each row's position
    onehot = jax.nn.one_hot(pos, S, dtype=ck.dtype)  # [B, S]
    upd = onehot[None, :, None, :, None]             # [1, B, 1, S, 1]
    new_kv = jnp.stack([k, v])[:, :, :, None, :]     # [2, B, H, 1, Dh]
    ck_out = ck * (1 - upd) + new_kv * upd

    key_pos = jnp.arange(S)[None, :]                 # [1, S]
    valid = key_pos <= pos[:, None]                  # [B, S]
    scores = jnp.einsum("bhd,bhsd->bhs", q, ck_out[0]).astype(jnp.float32)
    scores = scores / np.sqrt(Dh)
    if src_mask is not None:
        # [B|1, 1, 1, S'] additive mask, batch broadcastable
        m = arr(src_mask).astype(jnp.float32)
        m = m.reshape(m.shape[0], -1)[:, :S]          # [B|1, S]
        scores = scores + jnp.broadcast_to(m[:, None, :],
                                           scores.shape)
    scores = jnp.where(valid[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(ck.dtype)
    out = jnp.einsum("bhs,bhsd->bhd", probs, ck_out[1])
    out = out.reshape(B, H * Dh).astype(xv.dtype)
    if isinstance(x, Tensor):
        return Tensor(out), Tensor(ck_out)
    return out, ck_out


def fused_multi_head_attention(q, k, v, *, causal=True, **kw):
    from ....ops.pallas.flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal)

"""Fused functional ops (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, swiglu, fused_rotary_position_embedding, fused_moe, ...).

On TPU "fused" means: express the math in one traced region and let XLA's
fusion pass emit a single kernel — plus Pallas for the cases XLA can't fuse
(flash attention, ops/pallas/). The APIs keep the reference's names so
model code ports unchanged.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-5,
                   begin_norm_axis: int = -1, **kw):
    """fused_rms_norm.py equivalent; XLA fuses the whole thing."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=begin_norm_axis, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon) * norm_weight.astype(jnp.float32)
    if norm_bias is not None:
        out = out + norm_bias.astype(jnp.float32)
    return out.astype(dt)


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon: float = 1e-5,
                     begin_norm_axis: int = -1, **kw):
    dt = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=begin_norm_axis, keepdims=True)
    var = jnp.var(xf, axis=begin_norm_axis, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if norm_weight is not None:
        out = out * norm_weight.astype(jnp.float32)
    if norm_bias is not None:
        out = out + norm_bias.astype(jnp.float32)
    return out.astype(dt)


def swiglu(x, y=None):
    """swiglu.py: silu(x) * y; single-arg form splits x in half."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def fused_bias_act(x, bias=None, act_method: str = "gelu", **kw):
    if bias is not None:
        x = x + bias
    return {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "silu": jax.nn.silu, "swiglu": swiglu}[act_method](x)


def fused_linear(x, weight, bias=None, transpose_weight: bool = False):
    if transpose_weight:
        weight = weight.T
    out = x @ weight
    return out + bias if bias is not None else out


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation: str = "gelu"):
    if trans_x:
        x = x.T
    if trans_y:
        y = y.T
    return fused_bias_act(x @ y, bias, act_method=activation)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    rotary_emb_base: float = 10000.0):
    """fused_rotary_position_embedding equivalent on [B, T, H, Dh] tensors."""
    B, T, _, Dh = q.shape
    if cos is None or sin is None:
        half = Dh // 2
        inv = 1.0 / (rotary_emb_base **
                     (jnp.arange(0, half, dtype=jnp.float32) / half))
        pos = (position_ids if position_ids is not None
               else jnp.broadcast_to(jnp.arange(T), (B, T)))
        ang = pos[..., None].astype(jnp.float32) * inv       # [B, T, half]
        cos, sin = jnp.cos(ang), jnp.sin(ang)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]

    def rot(x):
        if x is None:
            return None
        half = x.shape[-1] // 2
        if use_neox_rotary_style:
            x1, x2 = x[..., :half], x[..., half:]
            out = jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        else:
            x1, x2 = x[..., 0::2], x[..., 1::2]
            r1 = x1 * cos - x2 * sin
            r2 = x2 * cos + x1 * sin
            out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
        return out.astype(x.dtype)

    return rot(q), rot(k), rot(v)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, *, top_k: int = 2,
              capacity_factor: float = 2.0, **kw):
    """cutlass fused_moe_kernel.cu equivalent: dense-dispatch grouped GEMM
    (see incubate.moe.functional.moe_ffn). ffn1 [E, D, 2F] packs gate|up."""
    from ...moe.functional import moe_ffn
    w_gate, w_up = jnp.split(ffn1_weight, 2, axis=-1)
    y, _ = moe_ffn(x, gate_weight, w_gate, w_up, ffn2_weight,
                   top_k=top_k, capacity_factor=capacity_factor)
    return y


def masked_multihead_attention(x, cache_kv=None, *args, **kw):
    raise NotImplementedError(
        "decode-time masked_multihead_attention: use "
        "paddle_tpu.ops.pallas.flash_attention with a KV cache "
        "(models/llama.py decode path)")


def fused_multi_head_attention(q, k, v, *, causal=True, **kw):
    from ....ops.pallas.flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal)

"""Experimental optimizers / training utilities.

Reference: python/paddle/incubate/optimizer/ (recompute.py, lookahead.py,
lbfgs.py, distributed_fused_lamb.py).
"""
from __future__ import annotations

import jax


def recompute(function, *args, use_reentrant: bool = True, **kwargs):
    """Activation recomputation (recompute.py). On TPU this is
    jax.checkpoint: forward runs without saving intermediates; they are
    rematerialized in the backward pass — HBM for FLOPs."""
    return jax.checkpoint(function)(*args, **kwargs)


class LookAhead:
    """lookahead.py: slow/fast weights. k inner steps, then slow update."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        self._slow = {}

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            pid = id(p)
            if pid not in self._slow:
                self._slow[pid] = p.data
            slow = self._slow[pid] + self.alpha * (p.data - self._slow[pid])
            self._slow[pid] = slow
            p.data = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, **kw):
        out = self.inner_optimizer.minimize(loss, **kw)
        self.step()
        return out

"""Automatic SParsity (ASP): n:m semi-structured weight sparsity.

Reference: python/paddle/incubate/asp/ (asp.py ``prune_model``/``decorate``,
utils.py ``get_mask_1d``/``check_mask_1d``/``calculate_density``). There the
point of 2:4 is Ampere's sparse tensor cores; TPU MXUs have no sparse mode,
so this module's contract is the *workflow and numerics*: computing n:m
masks, pruning, and keeping pruned weights at zero through training
(mask re-applied after every optimizer step by ``decorate``), so models
trained here deploy onto sparse-capable hardware with the same layout.

The mask math is vectorized jnp (group-of-m top-n by |w|) instead of the
reference's per-group numpy loops + itertools permutation search.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer import Layer

__all__ = [
    "calculate_density", "get_mask_1d", "check_mask_1d",
    "get_mask_2d_greedy", "get_mask_2d_best", "check_mask_2d",
    "check_sparsity", "prune_model", "decorate", "set_excluded_layers",
    "reset_excluded_layers",
]

_EXCLUDED: Dict[int, set] = {}  # id(model) -> {param names}
# id(param) -> (param, mask). The strong param reference is deliberate:
# it pins the id so a garbage-collected model's key can never be reused
# by a fresh parameter (Parameter has __slots__, so the mask can't live
# on the object and weakrefs aren't available either).
_MASKS: Dict[int, tuple] = {}


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference utils.py calculate_density)."""
    data = np.asarray(x.data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(data)) / max(data.size, 1)


def _group_mask_lastdim(w: jnp.ndarray, n: int, m: int) -> jnp.ndarray:
    """Keep the n largest-|w| entries in every group of m along the last
    dim. Vectorized: reshape to [..., G, m], rank within each group."""
    if w.shape[-1] % m:
        raise ValueError(f"last dim {w.shape[-1]} not divisible by m={m}")
    groups = w.reshape(w.shape[:-1] + (w.shape[-1] // m, m))
    # rank of each element within its group by |value| (desc)
    order = jnp.argsort(-jnp.abs(groups), axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks < n).astype(w.dtype)
    return mask.reshape(w.shape)


def get_mask_1d(mat, n: int = 2, m: int = 4):
    """n:m mask along rows of a 2-D matrix (reference utils.py
    get_mask_1d; there: per-group loop over m-chunks of each row)."""
    data = jnp.asarray(mat.data if isinstance(mat, Tensor) else mat)
    return _group_mask_lastdim(data, n, m)


def get_mask_2d_greedy(mat, n: int = 2, m: int = 4):
    """2-D n:m mask (reference utils.py get_mask_2d_greedy): within each
    m x m block keep entries largest-|w|-first, subject to every row AND
    every column of the block keeping at most n. Host numpy — mask
    construction is a one-off pruning step, not training compute."""
    data = np.asarray(mat.data if isinstance(mat, Tensor) else mat,
                      np.float64)
    if data.ndim != 2 or data.shape[0] % m or data.shape[1] % m:
        raise ValueError(f"2-D mask needs [R*{m}, C*{m}] matrix, "
                         f"got {data.shape}")
    mask = np.zeros_like(data)
    R, C = data.shape
    for r0 in range(0, R, m):
        for c0 in range(0, C, m):
            block = np.abs(data[r0:r0 + m, c0:c0 + m])
            order = np.dstack(np.unravel_index(
                np.argsort(-block, axis=None), (m, m)))[0]
            row_kept = np.zeros(m, np.int64)
            col_kept = np.zeros(m, np.int64)
            for i, j in order:
                if row_kept[i] < n and col_kept[j] < n:
                    mask[r0 + i, c0 + j] = 1.0
                    row_kept[i] += 1
                    col_kept[j] += 1
    return jnp.asarray(mask, jnp.float32)


def get_mask_2d_best(mat, n: int = 2, m: int = 4):
    """Reference's get_mask_2d_best refines the greedy 2-D mask with an
    exhaustive permutation search over block patterns; the greedy mask
    already satisfies the row+column n:m constraint (what hardware
    checks), so this build delegates to it — documented approximation,
    not a silent alias of the 1-D mask."""
    return get_mask_2d_greedy(mat, n, m)


def check_mask_2d(mat, n: int = 2, m: int = 4) -> bool:
    """True iff every m x m block keeps <= n per row and per column."""
    data = np.asarray(mat.data if isinstance(mat, Tensor) else mat)
    if data.ndim != 2 or data.shape[0] % m or data.shape[1] % m:
        return False
    R, C = data.shape
    blocks = data.reshape(R // m, m, C // m, m).transpose(0, 2, 1, 3)
    nz = blocks != 0
    return bool((nz.sum(-1) <= n).all() and (nz.sum(-2) <= n).all())


def check_mask_1d(mat, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group along rows has <= n nonzeros (reference
    utils.py check_mask_1d)."""
    data = np.asarray(mat.data if isinstance(mat, Tensor) else mat)
    if data.ndim < 1 or data.shape[-1] % m:
        return False
    groups = data.reshape(data.shape[:-1] + (data.shape[-1] // m, m))
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def check_sparsity(mat, n: int = 2, m: int = 4, func_name=None) -> bool:
    return check_mask_1d(mat, n, m)


def set_excluded_layers(model: Layer, param_names: List[str]):
    """Exclude parameters (by name substring) from pruning (reference
    asp.py set_excluded_layers)."""
    _EXCLUDED.setdefault(id(model), set()).update(param_names)


def reset_excluded_layers(model: Optional[Layer] = None):
    if model is None:
        _EXCLUDED.clear()
    else:
        _EXCLUDED.pop(id(model), None)


def _prunable_params(model: Layer):
    from ...nn.modules_basic import Linear
    excluded = _EXCLUDED.get(id(model), set())
    for lname, sub in model.named_sublayers(include_self=True):
        if not isinstance(sub, Linear):
            continue
        pname = f"{lname}.weight" if lname else "weight"
        if any(e in pname for e in excluded):
            continue
        yield pname, sub.weight


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every Linear weight in ``model`` (reference
    asp.py prune_model). Masks along the OUTPUT-feature groups of the
    [in, out] weight (the reduction-side grouping sparse hardware
    needs applies to W^T at deploy; the n:m property is symmetric per
    group so we mask the stored layout directly).

    Returns {param_name: mask}. When ``with_mask`` the masks are
    retained so ``decorate``-wrapped optimizers re-apply them after
    each step.
    """
    mask_fns = {"mask_1d": get_mask_1d, "mask_2d_greedy": get_mask_2d_greedy,
                "mask_2d_best": get_mask_2d_best}
    if mask_algo not in mask_fns:
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    masks = {}
    for pname, p in _prunable_params(model):
        if p._data.ndim != 2 or p._data.shape[-1] % m:
            continue
        if mask_algo != "mask_1d" and p._data.shape[0] % m:
            continue  # 2-D masks additionally need row-dim divisibility
        mask = mask_fns[mask_algo](p._data, n, m)
        p._data = p._data * mask
        masks[pname] = mask
        if with_mask:
            _MASKS[id(p)] = (p, mask)
    return masks


def decorate(optimizer):
    """Wrap an optimizer so pruned weights stay pruned: after every
    ``step()`` the stored masks are re-applied (reference asp.py
    decorate / OptimizerWithSparsityGuarantee — there masking happens
    via a masked-update pass; functionally identical since
    w*mask after step == masked gradient update for zeroed weights as
    the weights re-enter the next forward already pruned)."""
    orig_step = optimizer.step

    def step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for p in optimizer._param_list:
            entry = _MASKS.get(id(p))
            if entry is not None and entry[0] is p:
                p._data = p._data * entry[1].astype(p._data.dtype)
        return out

    optimizer.step = step
    return optimizer

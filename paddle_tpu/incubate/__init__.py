"""paddle_tpu.incubate — experimental surfaces (reference: python/paddle/incubate/).

Holds MoE (incubate/distributed/models/moe), fused functional ops
(incubate/nn/functional), and experimental optimizers.
"""
from . import moe  # noqa: F401
from .nn import functional as _fused  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
from .optimizer import LookAhead  # noqa: F401

# graph/segment ops (reference incubate/__init__.py re-exports; the
# implementations live with the other graph ops in paddle_tpu.geometric)
from ..geometric import (  # noqa: F401
    segment_sum, segment_mean, segment_max, segment_min,
    send_u_recv as graph_send_recv,
)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, **kw):
    from ..geometric import sample_neighbors
    raise NotImplementedError(
        "use paddle_tpu.geometric.sample_neighbors per hop (khop fusion "
        "is a GPU-hash-table optimization; hop-by-hop sampling is the "
        "TPU/host path)")


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, return_eids=return_eids)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count)


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss for IPU pipelines (reference
    incubate/autograd). Here: plain reduction."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 1):
        return x.sum()
    return x.mean()


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (reference
    incubate/operators/softmax_mask_fuse_upper_triangle.py — a CUDA
    fusion; XLA fuses the same expression on TPU)."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    T = d.shape[-1]
    mask = jnp.tril(jnp.ones((T, T), bool))
    logits = jnp.where(mask, d, jnp.finfo(d.dtype).min)
    import jax
    return Tensor(jax.nn.softmax(logits, axis=-1))


def softmax_mask_fuse(x, mask):
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    m = mask.data if isinstance(mask, Tensor) else jnp.asarray(mask)
    return Tensor(jax.nn.softmax(d + m, axis=-1))


class ModelAverage:
    """Parameter averaging over a training window (reference
    incubate/optimizer/modelaverage.py): accumulates running sums of
    params; apply()/restore() swap the average in and out for eval."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("parameters required")
        self._params = list(parameters)
        self._sums = {id(p): p._data * 0 for p in self._params}
        self._count = 0
        self._backup = None

    def step(self):
        for p in self._params:
            self._sums[id(p)] = self._sums[id(p)] + p._data
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            if self._count:
                p._data = (self._sums[id(p)] / self._count).astype(
                    p._data.dtype)

    def restore(self, executor=None):
        if self._backup:
            for p in self._params:
                p._data = self._backup[id(p)]
            self._backup = None


class inference:  # namespace shim: paddle.incubate.inference decorators
    @staticmethod
    def enable_inference_mode(fn=None, **kw):
        return fn if fn is not None else (lambda f: f)

"""paddle_tpu.incubate — experimental surfaces (reference: python/paddle/incubate/).

Holds MoE (incubate/distributed/models/moe), fused functional ops
(incubate/nn/functional), and experimental optimizers.
"""
from . import moe  # noqa: F401
from .nn import functional as _fused  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401

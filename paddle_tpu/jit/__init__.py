"""paddle.jit — dynamic-to-static capture and saved programs.

Reference: python/paddle/jit/api.py:195 (@to_static decorator), the SOT
bytecode frontend (jit/sot/translate.py:99 + eval_frame.c) and AST
frontend, lowering to PIR programs run by the StandaloneExecutor.

TPU-native redesign: capture IS jax tracing. ``to_static`` wraps a function
or Layer so the whole computation traces once into a single XLA module
(jax.jit); parameters become inputs so training keeps working — the tape
records ONE GradNode at the jit boundary whose vjp is the compiled backward
module. No bytecode interpreter is needed: Python control flow that is
tensor-independent folds at trace time (same effect as the reference's
graph-break-free path), and data-dependent control flow should use
lax.cond/scan via ops (matching XLA's compilation model — SURVEY.md §7).

``jit.save``/``jit.load`` serialize the traced program as StableHLO via
jax.export — the deployment artifact (reference: inference program +
AnalysisPredictor, SURVEY.md L9).
"""
from __future__ import annotations

import contextlib
import functools
import os
import pickle
from typing import Any, Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..autograd import tape as _tape
from ..ops import registry as _registry


class InputSpec:
    """Reference: paddle.static.InputSpec — symbolic input signature."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient


@contextlib.contextmanager
def _bind_params(params: List[Parameter], arrays):
    saved = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    try:
        yield
    finally:
        for p, s in zip(params, saved):
            p._data = s


_CAP_UNSET = object()  # sentinel: closure walk not yet run


class StaticFunction:
    """The compiled callable ``to_static`` returns (api.py
    StaticFunction equivalent). Collects the owning Layer's parameters as
    traced inputs; caches one XLA executable per input signature (the
    reference caches one program per spec the same way)."""

    def __init__(self, dygraph_function: Callable, layer=None,
                 input_spec=None, full_graph: bool = True):
        self._fn = dygraph_function
        self._layer = layer
        self._input_spec = input_spec
        # full_graph=False is the SOT graph-break analogue (reference:
        # jit/sot translate.py:99, eval_frame.c): if whole-graph tracing
        # fails, later calls run in SEGMENT mode (jit/segments.py) — ops
        # record into compiled subgraphs split at the concretisation
        # points, the break region runs eagerly. When gradients are
        # required the segmenter defers to plain eager (the tape), which
        # is the wholesale fallback (_fell_back). full_graph=True
        # surfaces the trace error instead.
        self._full_graph = full_graph
        self._bound_tensors: List = []
        self._cap_fp: Any = _CAP_UNSET  # closure-walk fingerprint
        self._captured_cache: List = []
        self._fell_back = False
        self._segmented = False
        self._seg_recorder = None
        functools.update_wrapper(self, dygraph_function)

        def _wrap(a):
            return (Tensor(a) if isinstance(a, (jax.Array, jax.core.Tracer,
                                                np.ndarray)) else a)

        def pure(param_arrays, arg_arrays, kwarg_arrays, static_kwargs):
            params = self._bound_tensors
            targs = [_wrap(a) for a in arg_arrays]
            tkw = {k: _wrap(v) for k, v in kwarg_arrays.items()}
            tkw.update(dict(static_kwargs))
            with _bind_params(params, param_arrays), _tape.no_grad():
                if self._layer is not None:
                    out = self._fn(self._layer, *targs, **tkw)
                else:
                    out = self._fn(*targs, **tkw)
            return jax.tree_util.tree_map(
                lambda t: t.data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        self._pure = pure
        self._jitted = jax.jit(pure, static_argnums=(3,))

    def _params(self) -> List[Parameter]:
        """Traced-input tensors: the owning Layer's parameters PLUS any
        tensors the function reads through its closure/globals (deep
        walk, static/nn.py _captured_tensors) — a free-variable tensor
        must become an operand, not a constant baked at trace time
        (VERDICT r4 Weak #1's to_static face).

        The deep walk is cached behind a per-call FINGERPRINT of the
        referenced closure/global values (their ids): reassigning a
        free-variable tensor changes the fingerprint and re-walks (so
        no stale lifting), while steady-state calls pay only the cheap
        getclosurevars + id scan, not the 100k-node traversal.
        Mutation NESTED inside an unchanged container is not detected —
        pass such tensors as arguments."""
        import inspect
        from ..static.nn import _captured_tensors
        params = (self._layer.parameters()
                  if self._layer is not None else [])
        try:
            cv = inspect.getclosurevars(self._fn)
            fp = tuple((name, id(v))
                       for scope in (cv.nonlocals, cv.globals)
                       for name, v in sorted(scope.items()))
        except TypeError:
            fp = _CAP_UNSET  # unfingerprintable: re-walk every call
        if fp is _CAP_UNSET or fp != self._cap_fp:
            seen = {id(p) for p in params}
            self._cap_fp = fp
            self._captured_cache = [
                t for t in _captured_tensors([self._fn])
                if id(t) not in seen]
        return params + self._captured_cache

    def _eager(self, *args, **kwargs):
        if self._layer is not None:
            return self._fn(self._layer, *args, **kwargs)
        return self._fn(*args, **kwargs)

    def _run_segmented(self, *args, **kwargs):
        from . import segments as _segments

        if self._seg_recorder is None:
            # tape_aware: ops that need gradient record too; each flushed
            # segment registers ONE GradNode whose backward is jax.vjp of
            # the segment — training through breaks runs compiled
            # subgraphs, not wholesale eager (reference: SOT compiles
            # training subgraphs, jit/sot/translate.py:99)
            self._seg_recorder = _segments.SegmentRecorder(tape_aware=True)
        with self._seg_recorder.active():
            out = self._eager(*args, **kwargs)
            return self._seg_recorder.finalize(out)

    @property
    def graph_break_stats(self):
        """Segment-capture counters: ops_recorded (inside compiled
        segments), ops_eager (at breaks), segments, cache_hits."""
        return dict(self._seg_recorder.stats) if self._seg_recorder else None

    def __call__(self, *args, **kwargs):
        if self._fell_back:
            return self._eager(*args, **kwargs)
        if self._segmented:
            return self._run_segmented(*args, **kwargs)
        params = self._bound_tensors = self._params()
        static_kwargs = tuple(
            (k, v) for k, v in kwargs.items()
            if not isinstance(v, (Tensor, jax.Array, np.ndarray)))
        dyn_kwargs = {k: v for k, v in kwargs.items()
                      if isinstance(v, (Tensor, jax.Array, np.ndarray))}

        def fn(param_arrays, *arg_arrays, **kwarr):
            return self._jitted(list(param_arrays), list(arg_arrays),
                                dict(kwarr), static_kwargs)

        try:
            return _registry.call_op(
                f"to_static:{getattr(self._fn, '__name__', 'fn')}",
                fn, (params,) + args, dyn_kwargs, differentiable=True)
        except jax.errors.JAXTypeError:
            if self._full_graph:
                raise
            # graph break: untraceable python (data-dependent control
            # flow, concretization). Re-run in segment mode: compiled
            # subgraphs around the break instead of wholesale eager.
            self._segmented = True
            return self._run_segmented(*args, **kwargs)

    # reference API surface
    @property
    def dygraph_function(self):
        return self._fn

    def concrete_program(self, *args, **kwargs):
        raise NotImplementedError("inspect via jax: .lower(...).as_text()")

    def lower(self, *args):
        """Return the StableHLO text for given example inputs."""
        self._bound_tensors = self._params()
        params = [p.data for p in self._bound_tensors]
        arrs = [a.data if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        return self._jitted.lower(params, arrs, {}, ()).as_text()


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph: bool = True, **kwargs):
    """Decorator/wrapper (api.py:195). ``backend`` accepted for source
    compat (the reference's CINN switch); compilation is always XLA here.
    ``full_graph=False`` enables the SOT-style fallback: untraceable
    functions run eagerly instead of raising."""
    from ..nn.layer import Layer

    def wrap(f):
        if isinstance(f, Layer):
            sf = StaticFunction(type(f).forward, layer=f,
                                input_spec=input_spec,
                                full_graph=full_graph)
            f.forward = sf
            return f
        return StaticFunction(f, input_spec=input_spec,
                              full_graph=full_graph)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(function=None):
    if function is None:
        return lambda f: f
    return function


def enable_to_static(flag: bool = True):
    pass


# ---------------------------------------------------------------------------
# save / load: StableHLO export
# ---------------------------------------------------------------------------

def save(layer_or_fn, path: str, input_spec: Optional[Sequence] = None,
         **configs):
    """Serialize program + params (reference jit.save → __model__ +
    params; here: jax.export StableHLO bytes + numpy params)."""
    from ..nn.layer import Layer
    from jax import export as jexport

    if isinstance(layer_or_fn, Layer):
        layer = layer_or_fn
        params = layer.parameters()
        if input_spec is None:
            raise ValueError("jit.save(layer, ...) needs input_spec")

        def pure(param_arrays, arg_arrays):
            with _bind_params(params, param_arrays), _tape.no_grad():
                out = layer(*[Tensor(a) for a in arg_arrays])
            return jax.tree_util.tree_map(
                lambda t: t.data if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        # InputSpec dims that are None/-1 export as SYMBOLIC dims (the
        # reference's dynamic-batch saved models). Naming rules, shared
        # one scope so equal names unify across inputs:
        #   * a STRING dim is that symbol verbatim — the explicit way to
        #     tie dims across inputs (InputSpec(["batch", 6]) twice);
        #   * None/-1 at axis 0 is "batch" for every input (multi-input
        #     models combine along the batch dim; distinct per-input
        #     symbols could never unify and the export would fail);
        #   * None/-1 elsewhere gets a unique symbol b{i}_{j}.
        scope = jexport.SymbolicScope()

        def sds(spec, i):
            dims = tuple(spec.shape)
            if any(d is None or isinstance(d, str)
                   or (isinstance(d, int) and d < 0) for d in dims):
                def sym(j, d):
                    if isinstance(d, str):
                        return d
                    if d is None or d < 0:
                        return "batch" if j == 0 else f"b{i}_{j}"
                    return str(d)
                txt = ", ".join(sym(j, d) for j, d in enumerate(dims))
                dims = jexport.symbolic_shape(txt, scope=scope)
            return jax.ShapeDtypeStruct(dims, jnp.dtype(str(spec.dtype)))

        args_shape = [sds(s, i) for i, s in enumerate(input_spec)]
        params_shape = [jax.ShapeDtypeStruct(p.data.shape, p.data.dtype)
                        for p in params]
        exported = jexport.export(jax.jit(pure))(params_shape, args_shape)
        blob = {
            "stablehlo": exported.serialize(),
            "params": [np.asarray(p.data) for p in params],
            "num_inputs": len(args_shape),
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(blob, f)
        return
    raise TypeError("jit.save expects a Layer (functions: use jax.export)")


class TranslatedLayer:
    """Loaded inference program (reference: translated_layer.py)."""

    def __init__(self, exported, params, num_inputs=None):
        self._exported = exported
        self._params = params
        self.num_inputs = num_inputs

    def __call__(self, *args):
        arrs = [a.data if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out = self._exported.call(self._params, arrs)
        return jax.tree_util.tree_map(Tensor, out)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("loaded StableHLO programs are inference-only")


def load(path: str, **configs) -> TranslatedLayer:
    from jax import export as jexport
    with open(path + ".pdmodel", "rb") as f:
        blob = pickle.load(f)
    exported = jexport.deserialize(blob["stablehlo"])
    params = [jnp.asarray(p) for p in blob["params"]]
    return TranslatedLayer(exported, params, blob.get("num_inputs"))


def ignore_module(modules):
    """Exempt modules from SOT tracing (reference jit/api.py
    ignore_module). Tracing here is jax-level; ignored modules are
    recorded so `to_static(full_graph=False)` falls back to eager when
    it hits them."""
    global _IGNORED_MODULES
    try:
        _IGNORED_MODULES |= set(modules)
    except NameError:
        _IGNORED_MODULES = set(modules)
    return list(_IGNORED_MODULES)


_IGNORED_MODULES: set = set()
_CODE_LEVEL = 0
_VERBOSITY = 0


def set_code_level(level=100, also_to_stdout=False):
    """Log transformed code (reference jit/dy2static logging). Tracing
    produces jaxprs, not rewritten source; the level gates jaxpr dumps
    from to_static."""
    global _CODE_LEVEL
    _CODE_LEVEL = level


def set_verbosity(level=0, also_to_stdout=False):
    global _VERBOSITY
    _VERBOSITY = level

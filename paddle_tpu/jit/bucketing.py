"""Dynamic-shape bucketing: bounded compilations for varying batch/seq.

Reference capability: symbolic shapes + bucketed lowering — PIR's
``DimExpr`` (paddle/pir/include/dialect/shape/utils/dim_expr.h:168-177)
lets one program cover a family of shapes, and CINN lowers bucketed
kernels per range (op_lowering_impl.h:61). XLA compiles static shapes
only, so the TPU-native policy is explicit: pad the dynamic dim up to a
bucket from a fixed ladder, trace ONE executable per bucket (log-many,
not per-size), and carry the true length so the function can mask.
This is the standard serving/variable-batch recipe on TPU.

    step = bucketed(fn, axis=0)            # pad+slice transparently
    step = bucketed(fn, axis=0, with_length=True)  # fn gets valid_len
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp


DEFAULT_BUCKETS = tuple(2 ** i for i in range(16))  # 1..32768


def bucket_size(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest bucket >= n (power-of-two ladder by default)."""
    for b in sorted(buckets or DEFAULT_BUCKETS):
        if b >= n:
            return b
    raise ValueError(f"{n} exceeds the largest bucket "
                     f"{max(buckets or DEFAULT_BUCKETS)}")


class BucketedFunction:
    """Wraps a jax-traceable function so calls with any size of the
    dynamic ``axis`` reuse one compiled executable per bucket."""

    def __init__(self, fn: Callable, axis: int = 0,
                 buckets: Optional[Sequence[int]] = None,
                 with_length: bool = False,
                 pad_value: float = 0):
        self._fn = fn
        self.axis = axis
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self.with_length = with_length
        self.pad_value = pad_value
        self._jit = jax.jit(self._padded_call)
        functools.update_wrapper(self, fn)

    def _padded_call(self, args, valid_len):
        if self.with_length:
            return self._fn(*args, valid_len=valid_len)
        return self._fn(*args)

    def _has_dim(self, a) -> bool:
        """Whether the bucketed axis exists on ``a`` (correct for
        negative axis too — ``ndim > ax`` would wrongly admit scalars
        when ax < 0)."""
        nd = getattr(a, "ndim", None)
        if nd is None:
            return False
        ax = self.axis
        return nd >= (-ax if ax < 0 else ax + 1)

    def __call__(self, *args):
        ax = self.axis
        arrays = [jnp.asarray(a) for a in args]
        sizes = {a.shape[ax] for a in arrays if self._has_dim(a)}
        if len(sizes) != 1:
            raise ValueError(
                f"all inputs must agree on dim {ax}; got {sizes}")
        n = sizes.pop()
        b = bucket_size(n, self.buckets)
        padded = []
        for a in arrays:
            if self._has_dim(a) and a.shape[ax] != b:
                pad = [(0, 0)] * a.ndim
                pad[ax % a.ndim] = (0, b - n)
                a = jnp.pad(a, pad, constant_values=self.pad_value)
            padded.append(a)
        out = self._jit(padded, jnp.int32(n))
        # slice outputs that kept the bucketed dim back to the true size
        def unpad(o):
            if (self._has_dim(o) and o.shape[ax] == b and b != n):
                return jax.lax.slice_in_dim(o, 0, n, axis=ax)
            return o
        return jax.tree_util.tree_map(unpad, out)


def bucketed(fn: Optional[Callable] = None, *, axis: int = 0,
             buckets: Optional[Sequence[int]] = None,
             with_length: bool = False, pad_value: float = 0):
    """Decorator form of :class:`BucketedFunction`."""
    def wrap(f):
        return BucketedFunction(f, axis=axis, buckets=buckets,
                                with_length=with_length,
                                pad_value=pad_value)
    return wrap(fn) if fn is not None else wrap

"""Partial-graph capture: compiled segments around graph breaks.

Reference: the SOT frontend (python/paddle/jit/sot/translate.py:99 +
eval_frame.c) splits a function at untraceable bytecode and keeps the
compiled subgraphs, running only the break region eagerly.

TPU-native redesign — no bytecode hook needed, because every tensor op
already dispatches through ``ops.registry.call_op``: when a
``to_static(full_graph=False)`` function fails whole-graph tracing, it
re-runs in SEGMENT mode. Ops are then *recorded* instead of executed
(outputs are Tensors holding ``LazyValue`` placeholders with shapes from
``jax.eval_shape``); the pending ops compile and execute as ONE jitted
segment only when a value is concretised — ``bool(t)`` / ``float(t)`` /
``t.numpy()`` at the data-dependent Python (the graph break) — and a new
segment starts after it. A function with one mid-function break thus
runs as two compiled XLA modules plus the eager break, instead of
falling back to per-op eager for everything (the round-3 behavior).

Training THROUGH breaks (tape_aware=True, the to_static default): a
recorded segment is a pure function of its slotted inputs, so ops that
need gradient are recorded too, and each flush registers ONE tape
GradNode over the whole segment — its backward is ``jax.vjp`` of the
replayed segment (reference counterpart: SOT compiles training
subgraphs, python/paddle/jit/sot/translate.py:99). A model with one
data-dependent break therefore trains as two compiled segments + the
eager break, with gradients flowing across both, instead of the
wholesale per-op eager fallback. ``create_graph`` double-backward
through a segment node is not supported (the node records no taped
forward closure) — the tape raises with that explanation.

Data-dependent output shapes still flush and run eagerly. Compiled
segments are cached by the recorded (op, input-signature) sequence, so
steady-state calls reuse the executable.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

# compiled-segment cache bound (LRU): a varying-shape inference server
# must not leak one pinned executable per (ops, shapes) signature forever
_EXEC_CACHE_MAX = 256


_FREEZE_PRIMITIVES = (int, float, complex, bool, str, bytes, type(None))


def _freeze_cell(v, depth: int = 0):
    """A hashable stand-in for one closure-cell value.

    Containers tuple-ize (static/nn.py's ``captured`` is a fresh LIST
    each call); Tensors key by OBJECT identity — safe because the
    recorded fns ``_bind`` those exact objects and read their values
    from traced arrays, so two closures over the same Tensor objects
    replay identically; callables key by identity (the registry's
    _VJP_CACHE precedent). Everything ELSE raises, forcing the id(fn)
    fallback: an arbitrary object frozen by identity would replay a
    cached trace after the object's attributes MUTATE (stale
    constant-baking) — the exact silent-wrongness class a cache must
    never introduce."""
    if depth > 3:
        raise TypeError("closure too deep")
    if isinstance(v, _FREEZE_PRIMITIVES):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_freeze_cell(x, depth + 1) for x in v)
    from ..core.tensor import Tensor
    if isinstance(v, Tensor):
        return ("__tensor__", id(v))
    if callable(v) and not hasattr(v, "shape"):
        hash(v)  # unhashable callables force the id(fn) fallback NOW,
        #          not later at the cache lookup
        # id distinguishes eq-equal-but-distinct callables; keeping v in
        # the key pins it so the id cannot be recycled after GC
        return ("__fn__", id(v), v)
    raise TypeError(f"unfreezable closure cell: {type(v).__name__}")


def _fn_cache_key(fn):
    """Key a recorded op's fn by its code object + frozen closure cells
    + frozen default args: APIs that build a fresh closure per call
    (static/nn.py cond/case/while close over a fresh ``captured`` list
    of stable Tensors + the user's stable branch callables) would never
    hit an ``id(fn)`` key — every flush would re-jit and permanently pin
    the dead closure (ADVICE r4). Defaults matter too (ADVICE r5):
    factory-made fns that capture via default args (``def f(x, y=s)``)
    share the code object with EMPTY closures — keying only on cells
    would collide them and replay another fn's baked constant. Falls
    back to identity when any cell/default defies freezing."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return id(fn)
    try:
        cells = tuple(_freeze_cell(c.cell_contents)
                      for c in (getattr(fn, "__closure__", None) or ()))
        dflts = tuple(_freeze_cell(v)
                      for v in (getattr(fn, "__defaults__", None) or ()))
        kwdflts = tuple(
            (k, _freeze_cell(v))
            for k, v in sorted((getattr(fn, "__kwdefaults__", None)
                                or {}).items()))
    except Exception:
        return id(fn)
    return (code, cells, dflts, kwdflts)

def current() -> Optional["SegmentRecorder"]:
    from ..ops import registry as _registry
    return _registry._ACTIVE_SEGMENT


class LazyValue:
    """Placeholder payload for a not-yet-executed op output. Quacks like
    an array for shape/dtype inspection; any VALUE access flushes the
    recorder's pending segment."""

    _is_lazy = True  # core.tensor.Tensor.__init__ passes us through

    __slots__ = ("_rec", "_aval", "_concrete", "__weakref__")

    def __init__(self, rec: "SegmentRecorder", aval):
        self._rec = rec
        self._aval = aval
        self._concrete = None

    # -- shape metadata (no flush) ------------------------------------
    @property
    def shape(self):
        return (self._concrete.shape if self._concrete is not None
                else self._aval.shape)

    @property
    def dtype(self):
        return (self._concrete.dtype if self._concrete is not None
                else self._aval.dtype)

    @property
    def ndim(self):
        return len(self.shape)

    # -- concretisation (flush) ----------------------------------------
    def _force(self):
        if self._concrete is None:
            self._rec.flush()
        assert self._concrete is not None
        return self._concrete

    def __array__(self, dtype=None):
        a = np.asarray(self._force())
        return a.astype(dtype) if dtype is not None else a

    def __bool__(self):
        return bool(self._force())

    def __float__(self):
        return float(self._force())

    def __int__(self):
        return int(self._force())

    def __index__(self):
        return int(self._force())

    def item(self, *args):
        return self._force().item(*args)

    def astype(self, dt):
        return self._force().astype(dt)

    def __repr__(self):
        if self._concrete is not None:
            return repr(self._concrete)
        return f"LazyValue(shape={self.shape}, dtype={self.dtype})"


class _Ref:
    """Argument slot in a recorded op: either a concrete input (position
    in the segment's input list) or a prior op's output."""

    __slots__ = ("kind", "i", "j")

    def __init__(self, kind: str, i: int, j: int = 0):
        self.kind, self.i, self.j = kind, i, j

    def key(self):
        return (self.kind, self.i, self.j)


class SegmentRecorder:
    """Records registry op calls; flushes them as one jitted module.

    ``tape_aware``: record ops that need gradient too, and register each
    flushed segment as ONE tape GradNode (backward = jax.vjp of the
    segment). Off, grad-needing ops flush the segment and run eagerly.
    """

    def __init__(self, tape_aware: bool = False):
        self.tape_aware = tape_aware
        self.pending: List[Tuple] = []      # (name, fn, args_t, kwargs_t)
        self.inputs: List[Any] = []         # concrete input arrays
        self._input_ids: Dict[int, int] = {}
        self._lazy_out: List[List[weakref.ref]] = []  # per-op LazyValues
        self._out_tensors: List[List[weakref.ref]] = []  # wrapping Tensors
        self._diff_pos: Dict[int, Any] = {}  # input slot -> grad Tensor
        self._exec_cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self.stats = {"ops_recorded": 0, "ops_eager": 0, "segments": 0,
                      "cache_hits": 0, "grad_segments": 0}

    # ------------------------------------------------------------ record --
    def _slot(self, payload) -> _Ref:
        if isinstance(payload, LazyValue):
            if payload._concrete is not None:
                return self._slot_concrete(payload._concrete)
            idx = next(i for i, outs in enumerate(self._lazy_out)
                       for r in outs
                       if r() is payload)
            j = next(j for j, r in enumerate(self._lazy_out[idx])
                     if r() is payload)
            return _Ref("op", idx, j)
        return self._slot_concrete(payload)

    def _slot_concrete(self, arr) -> _Ref:
        k = id(arr)
        if k not in self._input_ids:
            self._input_ids[k] = len(self.inputs)
            self.inputs.append(arr)
        return _Ref("in", self._input_ids[k])

    def record(self, name, fn, args, kwargs, need_grad: bool):
        """Try to record the op; return the wrapped lazy outputs, or
        ``None`` to make the caller run it eagerly (after our flush)."""
        from ..core.tensor import Tensor

        if need_grad and not self.tape_aware:
            self.flush()
            self.stats["ops_eager"] += 1
            return None

        def to_template(x):
            if isinstance(x, Tensor):
                ref = self._slot(x._data)
                if (need_grad and self.tape_aware and ref.kind == "in"
                        and (not x.stop_gradient or x._node is not None)):
                    # this concrete input needs gradient: it becomes one
                    # of the flushed segment's GradNode inputs. Gated on
                    # the OP's need_grad so no_grad() inference keeps the
                    # cheap plain-runner flush path
                    self._diff_pos[ref.i] = x
                return ref
            if hasattr(x, "shape") and hasattr(x, "dtype") and \
                    not np.isscalar(x):
                # raw array leaf (numpy/jax passed outside a Tensor):
                # slot it as a dynamic input — keying it as a "static"
                # would hash by repr, which numpy truncates (two big
                # arrays with equal printed corners would collide)
                return self._slot_concrete(jnp.asarray(x))
            return x

        is_ref = lambda x: isinstance(x, _Ref)
        try:
            args_t = jax.tree_util.tree_map(
                to_template, args,
                is_leaf=lambda x: isinstance(x, Tensor))
            kwargs_t = jax.tree_util.tree_map(
                to_template, kwargs,
                is_leaf=lambda x: isinstance(x, Tensor))

            def aval_of(ref):
                if ref.kind == "in":
                    v = self.inputs[ref.i]
                    return jax.ShapeDtypeStruct(v.shape, v.dtype)
                lv = self._lazy_out[ref.i][ref.j]()
                return jax.ShapeDtypeStruct(lv.shape, lv.dtype)

            # only the _Ref slots are dynamic; static args (axes, flags)
            # stay embedded python values — eval_shape must not see them
            # as inputs or they would become tracers
            refs = [x for x in jax.tree_util.tree_leaves(
                (args_t, kwargs_t), is_leaf=is_ref) if is_ref(x)]

            def fn_of(vals):
                it = iter(vals)
                sub = lambda x: next(it) if is_ref(x) else x
                a = jax.tree_util.tree_map(sub, args_t, is_leaf=is_ref)
                k = jax.tree_util.tree_map(sub, kwargs_t, is_leaf=is_ref)
                return fn(*a, **k)

            out_shape = jax.eval_shape(fn_of, [aval_of(r) for r in refs])
        except Exception:
            # untraceable/data-dependent op: run it (and everything it
            # depends on) eagerly
            self.flush()
            self.stats["ops_eager"] += 1
            return None

        flat_avals, treedef = jax.tree_util.tree_flatten(out_shape)
        lazies = [LazyValue(self, av) for av in flat_avals]
        self.pending.append((name, fn, args_t, kwargs_t, treedef))
        self._lazy_out.append([weakref.ref(lv) for lv in lazies])
        self.stats["ops_recorded"] += 1
        wrapped = [Tensor(lv, stop_gradient=not need_grad)
                   for lv in lazies]
        self._out_tensors.append([weakref.ref(t) for t in wrapped])
        return jax.tree_util.tree_unflatten(treedef, wrapped)

    # ------------------------------------------------------------- flush --
    def _signature(self):
        def hashable(x):
            try:
                hash(x)
                return x
            except TypeError:
                return repr(x)

        sig = []
        for name, fn, args_t, kwargs_t, treedef in self.pending:
            leaves = jax.tree_util.tree_leaves(
                (args_t, kwargs_t), is_leaf=lambda x: isinstance(x, _Ref))
            refs = tuple(x.key() for x in leaves if isinstance(x, _Ref))
            # statics distinguish e.g. transpose perms: same op + same
            # refs with different axes must NOT share an executable
            statics = tuple(hashable(x) for x in leaves
                            if not isinstance(x, _Ref))
            sig.append((name, _fn_cache_key(fn), refs, statics))
        in_sig = tuple((tuple(a.shape), str(jnp.result_type(a)))
                       for a in self.inputs)
        return (tuple(sig), in_sig)

    def _make_replay(self, pending):
        def replay(inputs):
            results = []  # per-op flat outputs

            def resolve(x):
                if isinstance(x, _Ref):
                    return (inputs[x.i] if x.kind == "in"
                            else results[x.i][x.j])
                return x

            for name, fn, args_t, kwargs_t, treedef in pending:
                a = jax.tree_util.tree_map(
                    resolve, args_t,
                    is_leaf=lambda x: isinstance(x, _Ref))
                k = jax.tree_util.tree_map(
                    resolve, kwargs_t,
                    is_leaf=lambda x: isinstance(x, _Ref))
                out = fn(*a, **k)
                results.append(jax.tree_util.tree_leaves(out))
            return results
        return replay

    def flush(self):
        """Compile + run the pending ops as one jitted segment; fill
        every produced LazyValue with its concrete array. With recorded
        grad inputs (tape_aware), also register the segment as one
        GradNode on the tape."""
        if not self.pending:
            self._reset_inputs()
            return
        pending = self.pending
        sig = self._signature()
        if self._diff_pos:
            results = self._flush_grad(pending, sig)
        else:
            runner = self._exec_cache.get(sig)
            if runner is None:
                runner = jax.jit(self._make_replay(pending))
                self._exec_cache[sig] = runner
                if len(self._exec_cache) > _EXEC_CACHE_MAX:
                    self._exec_cache.popitem(last=False)  # LRU eviction
            else:
                # the cached executable replays the ops IT was built
                # from — valid because the signature (ops, fn
                # code+closure values, refs, statics, input avals)
                # matches exactly
                self._exec_cache.move_to_end(sig)
                self.stats["cache_hits"] += 1
            results = runner(list(self.inputs))
        for outs, refs in zip(results, self._lazy_out):
            for arr, r in zip(outs, refs):
                lv = r()
                if lv is not None:
                    lv._concrete = arr
        self.stats["segments"] += 1
        self.pending = []
        self._lazy_out = []
        self._out_tensors = []
        self._reset_inputs()

    def _flush_grad(self, pending, sig):
        """Run the segment under ``jax.vjp`` and register ONE GradNode:
        the reference's SOT compiles training subgraphs the same way
        (jit/sot/translate.py:99) — here the subgraph's backward is the
        vjp of its replay function."""
        from ..autograd import tape as _tape

        diff_idx = sorted(self._diff_pos)
        diff_set = set(diff_idx)
        diff_tensors = [self._diff_pos[i] for i in diff_idx]
        n_inputs = len(self.inputs)
        nondiff = [a for i, a in enumerate(self.inputs)
                   if i not in diff_set]

        gkey = ("grad", sig, tuple(diff_idx))
        pair = self._exec_cache.get(gkey)
        if pair is None:
            replay = self._make_replay(pending)

            def seg_fwd(diff_arrays, nondiff_arrays):
                it_d, it_n = iter(diff_arrays), iter(nondiff_arrays)
                inputs = [next(it_d) if i in diff_set else next(it_n)
                          for i in range(n_inputs)]
                return replay(inputs)

            def seg_bwd(diff_arrays, nondiff_arrays, cot_tree):
                # vjp INSIDE the jit (the registry's _build_cached
                # pattern): the linearize+transpose happens once per
                # signature at compile time; steady-state flushes are
                # pure execution of the cached executables
                _, vjp = jax.vjp(lambda d: seg_fwd(d, nondiff_arrays),
                                 diff_arrays)
                (d,) = vjp(cot_tree)
                return tuple(d)

            pair = (jax.jit(seg_fwd), jax.jit(seg_bwd))
            self._exec_cache[gkey] = pair
            if len(self._exec_cache) > _EXEC_CACHE_MAX:
                self._exec_cache.popitem(last=False)
        else:
            self._exec_cache.move_to_end(gkey)
            self.stats["cache_hits"] += 1

        fwd_jit, bwd_jit = pair
        diff_arrays = [self.inputs[i] for i in diff_idx]
        results = fwd_jit(diff_arrays, nondiff)

        flat, treedef = jax.tree_util.tree_flatten(results)
        avals = [(o.shape, o.dtype) for o in flat]

        def vjp_fn(cot_tree, _b=bwd_jit, _d=diff_arrays, _n=nondiff):
            return _b(_d, _n, cot_tree)

        # pure_fn=None: create_graph double-backward through a segment
        # raises with the tape's explanatory error
        node = _tape.GradNode("jit_segment", vjp_fn, diff_tensors, avals,
                              treedef, pure_fn=None)
        # attach the node to every still-alive output Tensor; _out_index
        # is the global flat position across the segment's ops
        flat_pos = 0
        for op_out, trefs in zip(results, self._out_tensors):
            for j in range(len(op_out)):
                t = trefs[j]() if j < len(trefs) else None
                if t is not None and not t.stop_gradient:
                    t._node = node
                    t._out_index = flat_pos
                flat_pos += 1
        self.stats["grad_segments"] += 1
        return results

    def _reset_inputs(self):
        self.inputs = []
        self._input_ids = {}
        self._diff_pos = {}

    # ------------------------------------------------------------ scope --
    @contextmanager
    def active(self):
        # the active-recorder slot lives on the registry module so the
        # per-op dispatch reads one global instead of importing us
        from ..ops import registry as _registry
        prev = _registry._ACTIVE_SEGMENT
        _registry._ACTIVE_SEGMENT = self
        try:
            yield self
        finally:
            _registry._ACTIVE_SEGMENT = prev

    def finalize(self, out):
        """End-of-function flush: replace every LazyValue payload in the
        returned structure (and any still-pending ones) with arrays."""
        from ..core.tensor import Tensor
        self.flush()

        def harden(t):
            if isinstance(t, Tensor) and isinstance(t._data, LazyValue):
                t._data = t._data._force()
            return t

        return jax.tree_util.tree_map(
            harden, out, is_leaf=lambda t: isinstance(t, Tensor))

// Shared-memory ring buffer for multiprocess DataLoader transfer.
//
// Counterpart of the reference's shared-memory LoDTensor blobs between
// DataLoader worker processes and the trainer
// (python/paddle/io/dataloader/flat.py, multiprocess_utils.py, and the
// underlying paddle/fluid memory::allocation shm machinery): a worker
// process serialises a batch and pushes the bytes; the main process pops
// without an extra pickle-through-pipe copy.
//
// Single-producer single-consumer, lock-free (acquire/release atomics on
// head/tail), messages are length-prefixed byte spans that wrap around the
// ring. One ring per worker.
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct RingHdr {
  std::atomic<uint64_t> head;  // next write offset (producer-owned)
  std::atomic<uint64_t> tail;  // next read offset (consumer-owned)
  uint64_t capacity;           // data bytes
  std::atomic<uint32_t> closed;
  uint32_t _pad;
};

struct Ring {
  RingHdr* hdr;
  char* data;
  size_t map_size;
  bool owner;
  char name[256];
};

constexpr uint64_t kLenSize = 8;

Ring* map_ring(const char* name, uint64_t capacity, bool create) {
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  size_t map_size = sizeof(RingHdr) + capacity;
  if (create && ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!create) {
    struct stat st;
    if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(RingHdr)) {
      close(fd);
      return nullptr;
    }
    map_size = static_cast<size_t>(st.st_size);
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring;
  r->hdr = static_cast<RingHdr*>(mem);
  r->data = static_cast<char*>(mem) + sizeof(RingHdr);
  r->map_size = map_size;
  r->owner = create;
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  if (create) {
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->capacity = capacity;
    r->hdr->closed.store(0, std::memory_order_relaxed);
  }
  return r;
}

inline void ring_copy_in(Ring* r, uint64_t pos, const void* src, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  std::memcpy(r->data + off, src, first);
  if (n > first) std::memcpy(r->data, static_cast<const char*>(src) + first,
                             n - first);
}

inline void ring_copy_out(Ring* r, uint64_t pos, void* dst, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = n < cap - off ? n : cap - off;
  std::memcpy(dst, r->data + off, first);
  if (n > first) std::memcpy(static_cast<char*>(dst) + first, r->data,
                             n - first);
}

void sleep_us(long us) {
  struct timespec ts{0, us * 1000L};
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

void* pt_ring_create(const char* name, uint64_t capacity) {
  return map_ring(name, capacity, /*create=*/true);
}

void* pt_ring_attach(const char* name) {
  return map_ring(name, 0, /*create=*/false);
}

// returns 0 ok, -1 message larger than ring, -2 timeout, -3 closed
int pt_ring_push(void* rv, const void* buf, uint64_t n, int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(rv);
  uint64_t need = kLenSize + n;
  uint64_t cap = r->hdr->capacity;
  if (need > cap) return -1;
  int64_t waited_us = 0;
  for (;;) {
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (cap - (head - tail) >= need) {
      ring_copy_in(r, head, &n, kLenSize);
      ring_copy_in(r, head + kLenSize, buf, n);
      r->hdr->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (r->hdr->closed.load(std::memory_order_relaxed)) return -3;
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -2;
    sleep_us(200);
    waited_us += 200;
  }
}

// peek size of next message; -1 empty, -3 closed-and-drained
int64_t pt_ring_next_size(void* rv) {
  Ring* r = static_cast<Ring*>(rv);
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  if (head == tail) {
    return r->hdr->closed.load(std::memory_order_relaxed) ? -3 : -1;
  }
  uint64_t n;
  ring_copy_out(r, tail, &n, kLenSize);
  return static_cast<int64_t>(n);
}

// pop into buf (must hold next_size bytes); returns bytes or -1/-2/-3
int64_t pt_ring_pop(void* rv, void* buf, uint64_t bufsize,
                    int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(rv);
  int64_t waited_us = 0;
  for (;;) {
    int64_t sz = pt_ring_next_size(rv);
    if (sz >= 0) {
      if (static_cast<uint64_t>(sz) > bufsize) return -1;
      uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
      ring_copy_out(r, tail + kLenSize, buf, static_cast<uint64_t>(sz));
      r->hdr->tail.store(tail + kLenSize + static_cast<uint64_t>(sz),
                         std::memory_order_release);
      return sz;
    }
    if (sz == -3) return -3;
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -2;
    sleep_us(200);
    waited_us += 200;
  }
}

void pt_ring_close(void* rv) {
  static_cast<Ring*>(rv)->hdr->closed.store(1, std::memory_order_release);
}

// data capacity in bytes — producers size-check whole multi-part messages
// against this BEFORE pushing any part (a partial push would desync the
// header/payload framing)
uint64_t pt_ring_capacity(void* rv) {
  return static_cast<Ring*>(rv)->hdr->capacity;
}

// block until the ring has >= need free bytes (0), or timeout (-2) /
// closed (-3). Lets a producer reserve room for a whole multi-part
// message so the subsequent pushes cannot block mid-message (SPSC:
// free space only grows while the producer is idle).
int pt_ring_wait_space(void* rv, uint64_t need, int64_t timeout_ms) {
  Ring* r = static_cast<Ring*>(rv);
  if (need > r->hdr->capacity) return -1;
  int64_t waited_us = 0;
  for (;;) {
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (r->hdr->capacity - (head - tail) >= need) return 0;
    if (r->hdr->closed.load(std::memory_order_relaxed)) return -3;
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -2;
    sleep_us(200);
    waited_us += 200;
  }
}

void pt_ring_destroy(void* rv) {
  Ring* r = static_cast<Ring*>(rv);
  bool owner = r->owner;
  char name[256];
  std::memcpy(name, r->name, sizeof(name));
  munmap(r->hdr, r->map_size);
  if (owner) shm_unlink(name);
  delete r;
}

}  // extern "C"

// Auto-growth best-fit host allocator with stats.
//
// TPU-native counterpart of the reference's allocator stack
// (paddle/phi/core/memory/allocation/auto_growth_best_fit_allocator.h,
// allocator_facade.h, stats.h): device HBM is managed by XLA, so the native
// allocator's job here is pinned host staging buffers for the input
// pipeline (DataLoader batches, checkpoint IO) — large page-aligned chunks
// grown on demand, best-fit reuse, and the allocated/reserved/peak stat
// counters paddle.device.*.max_memory_allocated exposes.
//
// C ABI (ctypes-consumed; see paddle_tpu/core/native.py).
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <set>
#include <vector>

namespace {

constexpr size_t kAlign = 256;  // matches TPU-friendly host buffer alignment

inline size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

struct Chunk {
  void* base;
  size_t size;
};

struct FreeBlock {
  size_t size;
  void* ptr;
  bool operator<(const FreeBlock& o) const {
    return size != o.size ? size < o.size : ptr < o.ptr;
  }
};

class AutoGrowthBestFit {
 public:
  explicit AutoGrowthBestFit(size_t chunk_size)
      : chunk_size_(chunk_size ? align_up(chunk_size) : (64u << 20)) {}

  ~AutoGrowthBestFit() {
    for (auto& c : chunks_) std::free(c.base);
  }

  void* Alloc(size_t n) {
    if (n == 0) return nullptr;
    n = align_up(n);
    std::lock_guard<std::mutex> g(mu_);
    auto it = free_.lower_bound(FreeBlock{n, nullptr});
    if (it == free_.end()) {
      size_t grow = n > chunk_size_ ? n : chunk_size_;
      void* base = nullptr;
      if (posix_memalign(&base, kAlign, grow) != 0) return nullptr;
      chunks_.push_back({base, grow});
      reserved_ += grow;
      if (reserved_ > peak_reserved_) peak_reserved_ = reserved_;
      it = free_.insert(FreeBlock{grow, base}).first;
    }
    FreeBlock blk = *it;
    free_.erase(it);
    void* out = blk.ptr;
    if (blk.size > n) {  // split: remainder back to the free list
      free_.insert(
          FreeBlock{blk.size - n, static_cast<char*>(blk.ptr) + n});
    }
    size_t got = blk.size > n ? n : blk.size;
    in_use_[out] = got;
    allocated_ += got;
    if (allocated_ > peak_allocated_) peak_allocated_ = allocated_;
    return out;
  }

  bool Free(void* p) {
    if (p == nullptr) return true;
    std::lock_guard<std::mutex> g(mu_);
    auto it = in_use_.find(p);
    if (it == in_use_.end()) return false;
    size_t n = it->second;
    allocated_ -= n;
    in_use_.erase(it);
    // coalesce with adjacent free blocks
    char* lo = static_cast<char*>(p);
    char* hi = lo + n;
    for (auto fit = free_.begin(); fit != free_.end();) {
      char* fb = static_cast<char*>(fit->ptr);
      char* fe = fb + fit->size;
      if (fe == lo) {
        lo = fb;
        fit = free_.erase(fit);
      } else if (fb == hi) {
        hi = fe;
        fit = free_.erase(fit);
      } else {
        ++fit;
      }
    }
    free_.insert(FreeBlock{static_cast<size_t>(hi - lo), lo});
    return true;
  }

  void Stats(uint64_t* out4) {
    std::lock_guard<std::mutex> g(mu_);
    out4[0] = allocated_;
    out4[1] = reserved_;
    out4[2] = peak_allocated_;
    out4[3] = peak_reserved_;
  }

  void ResetPeak() {
    std::lock_guard<std::mutex> g(mu_);
    peak_allocated_ = allocated_;
    peak_reserved_ = reserved_;
  }

 private:
  std::mutex mu_;
  size_t chunk_size_;
  std::vector<Chunk> chunks_;
  std::set<FreeBlock> free_;
  std::map<void*, size_t> in_use_;
  uint64_t allocated_ = 0, reserved_ = 0;
  uint64_t peak_allocated_ = 0, peak_reserved_ = 0;
};

}  // namespace

extern "C" {

void* pt_alloc_create(uint64_t chunk_size) {
  return new (std::nothrow) AutoGrowthBestFit(chunk_size);
}

void pt_alloc_destroy(void* a) {
  delete static_cast<AutoGrowthBestFit*>(a);
}

void* pt_alloc_malloc(void* a, uint64_t n) {
  return static_cast<AutoGrowthBestFit*>(a)->Alloc(n);
}

int pt_alloc_free(void* a, void* p) {
  return static_cast<AutoGrowthBestFit*>(a)->Free(p) ? 0 : -1;
}

void pt_alloc_stats(void* a, uint64_t* out4) {
  static_cast<AutoGrowthBestFit*>(a)->Stats(out4);
}

void pt_alloc_reset_peak(void* a) {
  static_cast<AutoGrowthBestFit*>(a)->ResetPeak();
}

}  // extern "C"

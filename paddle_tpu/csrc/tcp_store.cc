// TCPStore: key-value rendezvous for multi-host bootstrap.
//
// Counterpart of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h, tcp_utils.cc): rank 0
// runs the server thread; every rank connects as a client and uses
// set/get/add/wait to exchange addresses and barrier before
// jax.distributed.initialize-style setup. Wire protocol: 1-byte op,
// u32 key length, key bytes, u32 value length, value bytes; replies are
// u32-length-prefixed blobs (add replies i64).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t { kSet = 0, kGet = 1, kAdd = 2, kWait = 3, kPing = 4 };

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_blob(int fd, std::string* out) {
  uint32_t n;
  if (!read_full(fd, &n, 4)) return false;
  out->resize(n);
  return n == 0 || read_full(fd, &(*out)[0], n);
}

bool write_blob(int fd, const std::string& s) {
  uint32_t n = static_cast<uint32_t>(s.size());
  return write_full(fd, &n, 4) &&
         (n == 0 || write_full(fd, s.data(), n));
}

class Server {
 public:
  explicit Server(int port) : port_(port) {}

  bool Start() {
    lfd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (lfd_ < 0) return false;
    int one = 1;
    setsockopt(lfd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(lfd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(lfd_, 128) != 0) {
      ::close(lfd_);
      return false;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    {
      // store stop_ under mu_ so a kWait handler cannot check the
      // predicate (false), lose the race to this store+notify, and then
      // park forever — the lost-wakeup window
      std::lock_guard<std::mutex> g(mu_);
      stop_.store(true);
    }
    // wake kWait handlers blocked on the condition variable (their
    // predicate checks stop_)
    cv_.notify_all();
    ::shutdown(lfd_, SHUT_RDWR);
    ::close(lfd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    // unblock Serve() threads parked in read() on live client sockets —
    // without this, Stop() deadlocks in join while a client is still
    // connected. Serve() deregisters each fd under threads_mu_ *before*
    // closing it, so every fd in the set is still open here.
    {
      std::lock_guard<std::mutex> g(threads_mu_);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    for (auto& t : client_threads_)
      if (t.joinable()) t.join();
  }

  ~Server() { if (!stop_.load()) Stop(); }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      int cfd = ::accept(lfd_, nullptr, nullptr);
      if (cfd < 0) break;
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(threads_mu_);
      client_fds_.push_back(cfd);
      client_threads_.emplace_back([this, cfd] { Serve(cfd); });
    }
  }

  void Serve(int fd) {
    for (;;) {
      uint8_t op;
      if (!read_full(fd, &op, 1)) break;
      std::string key, val;
      if (!read_blob(fd, &key)) break;
      if (op == kSet || op == kAdd) {
        if (!read_blob(fd, &val)) break;
      }
      if (op == kSet) {
        {
          std::lock_guard<std::mutex> g(mu_);
          kv_[key] = val;
        }
        cv_.notify_all();
        if (!write_blob(fd, "")) break;
      } else if (op == kGet) {
        std::string out;
        {
          std::lock_guard<std::mutex> g(mu_);
          auto it = kv_.find(key);
          if (it != kv_.end()) out = it->second;
        }
        if (!write_blob(fd, out)) break;
      } else if (op == kAdd) {
        int64_t delta;
        std::memcpy(&delta, val.data(), 8);
        int64_t now;
        {
          std::lock_guard<std::mutex> g(mu_);
          int64_t cur = 0;
          auto it = kv_.find(key);
          if (it != kv_.end()) std::memcpy(&cur, it->second.data(), 8);
          now = cur + delta;
          std::string enc(8, '\0');
          std::memcpy(&enc[0], &now, 8);
          kv_[key] = enc;
        }
        cv_.notify_all();
        if (!write_full(fd, &now, 8)) break;
      } else if (op == kWait) {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] {
          return stop_.load() || kv_.count(key) > 0;
        });
        lk.unlock();
        if (!write_blob(fd, "")) break;
      } else if (op == kPing) {
        if (!write_blob(fd, "pong")) break;
      }
    }
    {
      std::lock_guard<std::mutex> g(threads_mu_);
      for (auto it = client_fds_.begin(); it != client_fds_.end(); ++it) {
        if (*it == fd) { client_fds_.erase(it); break; }
      }
    }
    ::close(fd);
  }

  int port_;
  int lfd_ = -1;
  std::atomic<bool> stop_{false};
  std::thread accept_thread_;
  std::mutex threads_mu_;
  std::vector<std::thread> client_threads_;
  std::vector<int> client_fds_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> kv_;
};

struct Client {
  int fd;
};

}  // namespace

extern "C" {

void* pt_store_server_start(int port) {
  Server* s = new (std::nothrow) Server(port);
  if (s && !s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

void pt_store_server_stop(void* s) {
  Server* srv = static_cast<Server*>(s);
  srv->Stop();
  delete srv;
}

void* pt_store_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  // retry loop: server may come up later (reference tcp_utils retries too)
  int waited = 0;
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                   sizeof(addr)) != 0) {
    ::close(fd);
    if (waited >= timeout_ms) return nullptr;
    usleep(50 * 1000);
    waited += 50;
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Client* c = new Client{fd};
  return c;
}

void pt_store_disconnect(void* cv) {
  Client* c = static_cast<Client*>(cv);
  ::close(c->fd);
  delete c;
}

int pt_store_set(void* cv, const char* key, const uint8_t* val, uint32_t n) {
  Client* c = static_cast<Client*>(cv);
  uint8_t op = kSet;
  std::string k(key), v(reinterpret_cast<const char*>(val), n), reply;
  if (!write_full(c->fd, &op, 1) || !write_blob(c->fd, k) ||
      !write_blob(c->fd, v) || !read_blob(c->fd, &reply))
    return -1;
  return 0;
}

// returns length (>=0) into out (caller-sized); -1 on connection error;
// -(size)-2 when the reply needs a bigger buffer (caller reallocs and
// retries — the protocol is stateless request/response, so a retry simply
// re-requests the key)
int64_t pt_store_get(void* cv, const char* key, uint8_t* out,
                     uint32_t out_cap) {
  Client* c = static_cast<Client*>(cv);
  uint8_t op = kGet;
  std::string k(key), reply;
  if (!write_full(c->fd, &op, 1) || !write_blob(c->fd, k) ||
      !read_blob(c->fd, &reply))
    return -1;
  if (reply.size() > out_cap)
    return -static_cast<int64_t>(reply.size()) - 2;
  std::memcpy(out, reply.data(), reply.size());
  return static_cast<int64_t>(reply.size());
}

int64_t pt_store_add(void* cv, const char* key, int64_t delta) {
  Client* c = static_cast<Client*>(cv);
  uint8_t op = kAdd;
  std::string k(key), v(8, '\0');
  std::memcpy(&v[0], &delta, 8);
  int64_t result;
  if (!write_full(c->fd, &op, 1) || !write_blob(c->fd, k) ||
      !write_blob(c->fd, v) || !read_full(c->fd, &result, 8))
    return INT64_MIN;
  return result;
}

int pt_store_wait(void* cv, const char* key) {
  Client* c = static_cast<Client*>(cv);
  uint8_t op = kWait;
  std::string k(key), reply;
  if (!write_full(c->fd, &op, 1) || !write_blob(c->fd, k) ||
      !read_blob(c->fd, &reply))
    return -1;
  return 0;
}

}  // extern "C"

"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up redesign (JAX/XLA/Pallas/pjit-idiomatic) offering the capability
surface of the PaddlePaddle reference (see SURVEY.md at the repo root): eager
tensors with tape autograd, a pure-JAX op library fused by XLA, capture/compile
via jit, hybrid + auto parallelism over jax.sharding meshes, DataLoader, AMP,
distributed checkpointing, and model libraries.

Top-level namespace mirrors `paddle.*`.
"""
from __future__ import annotations

import importlib

__version__ = "0.3.0"

from .core.tensor import Tensor, Parameter
from .core import dtype as _dtype_mod
from .core.dtype import (
    DType, bool_, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, iinfo, finfo,
)
from .core.generator import seed, Generator
from .core.flags import get_flags, set_flags
from .core.containers import (TensorArray, SelectedRows, create_array,
                              array_write, array_read, array_length)
from .autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad
from .autograd.tape import backward as _backward
from .framework import (get_default_device, set_device, get_device,
                        device_count, is_compiled_with_tpu,
                        CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace)

# the op library (also installs Tensor methods/dunders)
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401

bool = bool_  # paddle.bool


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Free-standing parameter factory (reference:
    python/paddle/tensor/creation.py create_parameter)."""
    from .nn.layer import make_parameter
    return make_parameter(shape, dtype or "float32", attr=attr,
                          is_bias=is_bias,
                          default_initializer=default_initializer,
                          name=name or "")


_LAZY_SUBMODULES = (
    "nn", "optimizer", "io", "amp", "jit", "distributed", "vision", "metric",
    "incubate", "models", "profiler", "autograd", "static", "sparse", "fft",
    "signal", "linalg", "text", "audio", "hapi", "device", "regularizer",
    "distribution", "quantization", "geometric", "onnx", "utils", "version",
    "callbacks", "parallel", "strings", "hub", "sysconfig", "_C_ops",
)
from .batch import batch  # noqa: E402


def ParamAttr(*args, **kwargs):  # noqa: N802 (reference class name)
    """paddle.ParamAttr (reference python/paddle/base/param_attr.py)."""
    from .nn.initializer import ParamAttr as _PA
    return _PA(*args, **kwargs)


dtype = _dtype_mod.DType  # paddle.dtype: the framework dtype type


def get_rng_state(device=None):
    """Opaque RNG state list (reference paddle.get_rng_state)."""
    from .core import generator
    return [generator.default_generator().get_state()]


def set_rng_state(state_list, device=None):
    from .core import generator
    generator.default_generator().set_state(state_list[0])


get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Mirrors numpy printoptions (Tensor repr routes through numpy)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op: this build installs no signal handlers (the reference
    hooks SIGSEGV etc. for C++ stack reports; XLA/JAX do not)."""


def check_shape(x):
    """Shape sanity assertion used by reference debugging utilities."""
    s = tuple(x.shape)
    if any(int(d) < 0 for d in s):
        raise ValueError(f"tensor has negative dimension: {s}")
    return s


class LazyGuard:
    """Deferred-initialization scope (reference paddle.LazyGuard defers
    parameter materialization until `layer.forward`). Functional JAX
    arrays are cheap to materialize and there is no separate
    startup-program phase to defer into, so entering the scope is a
    no-op kept for API compatibility; parameters are created eagerly."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Per-layer FLOPs estimate (reference paddle.flops / hapi.summary).
    Counts the MXU-relevant layers: conv (2*k*k*cin*cout*Ho*Wo),
    linear (2*in*out), matmul-free layers are 0."""
    import numpy as _np
    from .nn import Conv2D, Linear
    total = [0]
    hooks = []

    def conv_hook(layer, inp, out):
        k = int(_np.prod(layer.kernel_size))
        cin = layer.in_channels // layer.groups
        total[0] += 2 * k * cin * layer.out_channels * int(
            _np.prod(out.shape[2:])) * out.shape[0]

    def linear_hook(layer, inp, out):
        total[0] += 2 * layer.in_features * layer.out_features * int(
            _np.prod(out.shape[:-1]))

    for sub in net.sublayers(include_self=True):
        if isinstance(sub, Conv2D):
            hooks.append(sub.register_forward_post_hook(conv_hook))
        elif isinstance(sub, Linear):
            hooks.append(sub.register_forward_post_hook(linear_hook))
    import jax.numpy as jnp
    x = Tensor(jnp.zeros(tuple(input_size), jnp.float32))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]}")
    return total[0]


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi import Model
        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel
        return DataParallel
    if name == "summary":
        from .hapi.summary import summary
        return summary
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def is_grad_enabled_():
    from .autograd import tape
    return tape.grad_enabled()


def disable_static(place=None):
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu has no legacy static-graph mode; use paddle_tpu.jit.to_static "
        "(program capture compiles to a single XLA module)")


def in_dynamic_mode() -> bool:
    return True


def save(obj, path, protocol=4, **configs):
    from .framework import io as _io
    return _io.save(obj, path, protocol=protocol, **configs)


def load(path, **configs):
    from .framework import io as _io
    return _io.load(path, **configs)

"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up redesign (JAX/XLA/Pallas/pjit-idiomatic) offering the capability
surface of the PaddlePaddle reference (see SURVEY.md at the repo root): eager
tensors with tape autograd, a pure-JAX op library fused by XLA, capture/compile
via jit, hybrid + auto parallelism over jax.sharding meshes, DataLoader, AMP,
distributed checkpointing, and model libraries.

Top-level namespace mirrors `paddle.*`.
"""
from __future__ import annotations

import importlib

__version__ = "0.1.0"

from .core.tensor import Tensor, Parameter
from .core import dtype as _dtype_mod
from .core.dtype import (
    DType, bool_, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, iinfo, finfo,
)
from .core.generator import seed, Generator
from .core.flags import get_flags, set_flags
from .core.containers import (TensorArray, SelectedRows, create_array,
                              array_write, array_read, array_length)
from .autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad
from .autograd.tape import backward as _backward
from .framework import get_default_device, set_device, get_device, device_count, is_compiled_with_tpu

# the op library (also installs Tensor methods/dunders)
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401

bool = bool_  # paddle.bool


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Free-standing parameter factory (reference:
    python/paddle/tensor/creation.py create_parameter)."""
    from .nn.layer import make_parameter
    return make_parameter(shape, dtype or "float32", attr=attr,
                          is_bias=is_bias,
                          default_initializer=default_initializer,
                          name=name or "")


_LAZY_SUBMODULES = (
    "nn", "optimizer", "io", "amp", "jit", "distributed", "vision", "metric",
    "incubate", "models", "profiler", "autograd", "static", "sparse", "fft",
    "signal", "linalg", "text", "audio", "hapi", "device", "regularizer",
    "distribution", "quantization", "geometric", "onnx", "utils", "version",
    "callbacks", "parallel", "strings", "hub", "sysconfig", "_C_ops",
)
from .batch import batch  # noqa: E402


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "Model":
        from .hapi import Model
        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel
        return DataParallel
    if name == "summary":
        from .hapi.summary import summary
        return summary
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def is_grad_enabled_():
    from .autograd import tape
    return tape.grad_enabled()


def disable_static(place=None):
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu has no legacy static-graph mode; use paddle_tpu.jit.to_static "
        "(program capture compiles to a single XLA module)")


def in_dynamic_mode() -> bool:
    return True


def save(obj, path, protocol=4, **configs):
    from .framework import io as _io
    return _io.save(obj, path, protocol=protocol, **configs)


def load(path, **configs):
    from .framework import io as _io
    return _io.load(path, **configs)

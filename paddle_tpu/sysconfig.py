"""paddle.sysconfig — build introspection.

Reference: python/paddle/sysconfig.py (get_include/get_lib for
compiling extensions against the framework).
"""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of C/C++ headers for building native extensions
    (the XLA-FFI custom-kernel path, utils/cpp_extension.py)."""
    return os.path.join(_ROOT, "csrc")


def get_lib() -> str:
    """Directory holding the framework's compiled native libraries."""
    return os.path.join(_ROOT, "csrc", "build")

"""paddle_tpu.vision — vision models, transforms, datasets, ops.

Reference: python/paddle/vision/ (models/, transforms/, datasets/, ops.py).
Model definitions live in paddle_tpu.models and are re-exported here under
the reference's paths.
"""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from .models import (  # noqa: F401
    LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2, resnext50_32x4d, resnext101_64x4d,
)


def get_image_backend() -> str:
    return "numpy"


def set_image_backend(backend: str) -> None:
    if backend not in ("numpy", "cv2", "pil"):
        raise ValueError(f"unknown image backend {backend!r}")

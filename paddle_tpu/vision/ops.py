"""paddle.vision.ops — detection/vision operators.

Reference: python/paddle/vision/ops.py (nms:1934, matrix_nms:2358,
roi_align:1705, roi_pool:1572, psroi_pool:1441, box_coder:584,
prior_box:438, yolo_box:277, deform_conv2d:766,
distribute_fpn_proposals:1175, ConvNormActivation:1877) over CUDA
kernels in paddle/phi/kernels/gpu/ (nms_kernel.cu, roi_align_kernel.cu,
deformable_conv_kernel.cu ...).

TPU-native design notes:
- Greedy NMS is sequential by definition; the TPU shape is an O(N^2)
  IoU matrix + a lax.fori_loop over boxes flipping a keep mask — no
  host round trips, one fused program. matrix_nms is embarrassingly
  parallel (its decay is a matrix expression) and is the TPU-preferred
  suppressor.
- roi_align/psroi_pool are bilinear gathers: vmap over RoIs of a
  sampling-grid gather — XLA turns these into batched dynamic-slices.
- deform_conv2d = bilinear sampling at offset positions + an einsum
  against the kernel — the MXU does the contraction; there is no
  im2col scratch buffer.
- read_file/decode_jpeg are host-side file IO in the reference and out
  of scope for the accelerator runtime (raise with guidance).
"""
from __future__ import annotations

import itertools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from .. import nn

__all__ = ["nms", "matrix_nms", "roi_align", "roi_pool", "psroi_pool",
           "yolo_loss", "generate_proposals",
           "box_coder", "prior_box", "yolo_box", "deform_conv2d",
           "DeformConv2D", "RoIAlign", "RoIPool", "PSRoIPool",
           "ConvNormActivation", "distribute_fpn_proposals"]


def _arr(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


def _box_iou_matrix(a, b):
    """IoU of [N,4] x [M,4] xyxy boxes -> [N,M]."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None):
    """Greedy hard NMS (reference ops.py:1934). Returns kept indices,
    score-descending. Per-category when category_idxs/categories given
    (boxes of different categories never suppress each other)."""
    b = _arr(boxes).astype(jnp.float32)
    n = b.shape[0]
    s = (_arr(scores).astype(jnp.float32) if scores is not None
         else jnp.arange(n, 0, -1, dtype=jnp.float32))
    order = jnp.argsort(-s)
    b_sorted = b[order]
    iou = _box_iou_matrix(b_sorted, b_sorted)
    if category_idxs is not None:
        cat = _arr(category_idxs)[order]
        iou = jnp.where(cat[:, None] == cat[None, :], iou, 0.0)

    def body(i, keep):
        # suppress i if any higher-scored kept box overlaps too much
        over = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~over.any())

    keep = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    kept_sorted = np.asarray(keep)
    idx = np.asarray(order)[kept_sorted]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(jnp.asarray(idx, jnp.int32))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference ops.py:2358, SOLOv2): fully-parallel decay
    of each box's score by its overlaps with higher-scored same-class
    boxes — a matrix expression, no sequential loop; the TPU-preferred
    suppressor. bboxes [B,N,4], scores [B,C,N]."""
    bb = _arr(bboxes).astype(jnp.float32)
    sc = _arr(scores).astype(jnp.float32)
    B, C, N = sc.shape
    outs, indices, rois_num = [], [], []
    for bi in range(B):  # batch is host-level (ragged outputs)
        per_class = []
        for ci in range(C):
            if ci == background_label:
                continue
            s = sc[bi, ci]
            valid = s > score_threshold
            order = jnp.argsort(-s)
            if nms_top_k > 0:
                order = order[:nms_top_k]
            s_s, b_s = s[order], bb[bi][order]
            iou = _box_iou_matrix(b_s, b_s)
            upper = jnp.triu(iou, k=1)  # [i,j]: overlap of higher i on j
            # compensation for row i = its own worst overlap with anything
            # scored above it, i.e. the COLUMN max (matrix_nms_kernel.cc:120
            # iou_max); decay_score (:70,:77) then divides/exponentiates
            # per (pair iou, row compensation) and the column min wins
            comp = upper.max(axis=0)
            if use_gaussian:
                decay = jnp.exp((comp[:, None] ** 2 - upper ** 2)
                                * gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - upper) / jnp.maximum(1 - comp[:, None], 1e-10)
                         ).min(axis=0)
            decay = jnp.minimum(decay, 1.0)
            dec_s = s_s * decay * valid[order]
            keepm = dec_s > post_threshold
            k_idx = np.nonzero(np.asarray(keepm))[0]
            for j in k_idx:
                per_class.append((float(dec_s[j]), ci, int(order[j])))
        per_class.sort(key=lambda t: -t[0])
        if keep_top_k > 0:
            per_class = per_class[:keep_top_k]
        out = np.asarray([[c, s] + list(np.asarray(bb[bi][i]))
                          for s, c, i in per_class], np.float32
                         ).reshape(-1, 6)
        outs.append(out)
        indices.extend(i + bi * N for _, _, i in per_class)
        rois_num.append(len(per_class))
    out = Tensor(jnp.asarray(np.concatenate(outs, axis=0)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(indices, jnp.int32)))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(rois_num, jnp.int32)))
    return tuple(res) if len(res) > 1 else out


def _bilinear_sample(feat, y, x):
    """feat [C,H,W]; y/x arbitrary same-shaped grids -> [C, *grid]."""
    C, H, W = feat.shape
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly, lx = y - y0, x - x0
    y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    # out-of-range samples contribute zero (reference roi_align border)
    inb = ((y > -1) & (y < H) & (x > -1) & (x < W)).astype(feat.dtype)
    return ((v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
             + v10 * ly * (1 - lx) + v11 * ly * lx) * inb)


def _rois_to_batch(boxes, boxes_num, B):
    """[sum(n),4] + per-image counts -> per-roi batch index."""
    bn = np.asarray(_arr(boxes_num), np.int64)
    return jnp.asarray(np.repeat(np.arange(B), bn), jnp.int32)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference ops.py:1705 over roi_align_kernel.cu):
    average of bilinear samples on a regular grid inside each bin.

    ``sampling_ratio=-1`` approximation: the reference samples
    ``ceil(roi_size/output_size)`` points per bin — a data-dependent
    count that would force dynamic shapes under XLA. This build uses a
    fixed 2x2 grid instead (exact for RoIs up to 2x the output grid;
    coarser sampling, not wrong values, beyond that). Pass an explicit
    ``sampling_ratio`` for a denser static grid."""
    feat = _arr(x)
    rois = _arr(boxes).astype(jnp.float32)
    B, C, H, W = feat.shape
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = _rois_to_batch(boxes, boxes_num, B)
    off = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        gy = (y1 + bin_h * (jnp.arange(ph)[:, None, None, None]
                            + (jnp.arange(sr)[None, None, :, None] + 0.5) / sr))
        gx = (x1 + bin_w * (jnp.arange(pw)[None, :, None, None]
                            + (jnp.arange(sr)[None, None, None, :] + 0.5) / sr))
        yy = jnp.broadcast_to(gy, (ph, pw, sr, sr))
        xx = jnp.broadcast_to(gx, (ph, pw, sr, sr))
        samples = _bilinear_sample(feat[bi], yy, xx)   # [C,ph,pw,sr,sr]
        return samples.mean(axis=(-1, -2))

    return Tensor(jax.vmap(one_roi)(rois, batch_idx))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (reference ops.py:1572): max over quantized bins."""
    feat = _arr(x)
    rois = _arr(boxes).astype(jnp.float32)
    B, C, H, W = feat.shape
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = _rois_to_batch(boxes, boxes_num, B)
    # dense-grid formulation (static shapes): for every output bin,
    # max over the full feature map masked to the bin's rectangle
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi, bi):
        x1 = jnp.floor(roi[0] * spatial_scale)
        y1 = jnp.floor(roi[1] * spatial_scale)
        x2 = jnp.ceil(roi[2] * spatial_scale)
        y2 = jnp.ceil(roi[3] * spatial_scale)
        bh = jnp.maximum((y2 - y1) / ph, 1e-6)
        bw = jnp.maximum((x2 - x1) / pw, 1e-6)
        by = jnp.clip(jnp.floor((ys[None, :] - y1) / bh), -1, ph)  # [1,H]
        bx = jnp.clip(jnp.floor((xs[None, :] - x1) / bw), -1, pw)
        fy = (by == jnp.arange(ph, dtype=jnp.float32)[:, None])    # [ph,H]
        fx = (bx == jnp.arange(pw, dtype=jnp.float32)[:, None])    # [pw,W]
        m = fy[:, None, :, None] & fx[None, :, None, :]            # [ph,pw,H,W]
        vals = jnp.where(m[None], feat[bi][:, None, None, :, :],
                         -jnp.inf)
        out = vals.max(axis=(-1, -2))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return Tensor(jax.vmap(one_roi)(rois, batch_idx))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference ops.py:1441): output
    channel (c, i, j) averages input channel c*ph*pw + i*pw + j over
    bin (i, j)."""
    feat = _arr(x)
    rois = _arr(boxes).astype(jnp.float32)
    B, C, H, W = feat.shape
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    if C % (ph * pw):
        raise ValueError(f"channels {C} must be divisible by "
                         f"output_size^2 {ph * pw}")
    Cout = C // (ph * pw)
    batch_idx = _rois_to_batch(boxes, boxes_num, B)
    ys = jnp.arange(H, dtype=jnp.float32) + 0.5
    xs = jnp.arange(W, dtype=jnp.float32) + 0.5

    def one_roi(roi, bi):
        x1, y1, x2, y2 = roi * spatial_scale
        bh = jnp.maximum((y2 - y1) / ph, 0.1)
        bw = jnp.maximum((x2 - x1) / pw, 0.1)
        fmap = feat[bi].reshape(Cout, ph, pw, H, W)
        by = jnp.floor((ys - y1) / bh)          # [H]
        bx = jnp.floor((xs - x1) / bw)          # [W]
        fy = (by[None, :] == jnp.arange(ph, dtype=jnp.float32)[:, None])
        fx = (bx[None, :] == jnp.arange(pw, dtype=jnp.float32)[:, None])
        m = (fy[:, None, :, None] & fx[None, :, None, :]).astype(feat.dtype)
        s = jnp.einsum("cijhw,ijhw->cij", fmap, m)
        cnt = jnp.maximum(m.sum((-1, -2)), 1.0)
        return s / cnt

    return Tensor(jax.vmap(one_roi)(rois, batch_idx))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against anchors (reference ops.py:584)."""
    pb = _arr(prior_box).astype(jnp.float32)
    tb = _arr(target_box).astype(jnp.float32)
    pbv = (None if prior_box_var is None
           else jnp.asarray(_arr(prior_box_var), jnp.float32))
    norm = 0.0 if box_normalized else 1.0
    pw = pb[..., 2] - pb[..., 0] + norm
    ph_ = pb[..., 3] - pb[..., 1] + norm
    pcx = pb[..., 0] + pw * 0.5
    pcy = pb[..., 1] + ph_ * 0.5
    if code_type == "encode_center_size":
        tw = tb[..., 2] - tb[..., 0] + norm
        th = tb[..., 3] - tb[..., 1] + norm
        tcx = tb[..., 0] + tw * 0.5
        tcy = tb[..., 1] + th * 0.5
        out = jnp.stack([(tcx[:, None] - pcx[None]) / pw[None],
                         (tcy[:, None] - pcy[None]) / ph_[None],
                         jnp.log(tw[:, None] / pw[None]),
                         jnp.log(th[:, None] / ph_[None])], axis=-1)
        if pbv is not None:
            out = out / pbv
        return Tensor(out)
    if code_type == "decode_center_size":
        d = tb if pbv is None else tb * pbv
        if tb.ndim == 3:
            # priors broadcast along `axis` of [.., .., 4] deltas
            expand = (slice(None), None) if axis == 0 else (None, slice(None))
            pcx, pcy, pw, ph_ = (v[expand] for v in (pcx, pcy, pw, ph_))
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph_ + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph_
        return Tensor(jnp.stack([cx - w * 0.5, cy - h * 0.5,
                                 cx + w * 0.5 - norm,
                                 cy + h * 0.5 - norm], axis=-1))
    raise ValueError(f"unknown code_type {code_type!r}")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD anchor generation (reference ops.py:438). Pure host math."""
    feat = _arr(input)
    img = _arr(image)
    H, W = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = steps[1] or ih / H
    step_w = steps[0] or iw / W
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    variances = []
    for y, x in itertools.product(range(H), range(W)):
        cx = (x + offset) * step_w
        cy = (y + offset) * step_h
        cell = []
        for si, ms in enumerate(min_sizes):
            ms = float(ms)
            if min_max_aspect_ratios_order:
                cell.append((cx, cy, ms, ms))
                if max_sizes:
                    big = np.sqrt(ms * float(max_sizes[si]))
                    cell.append((cx, cy, big, big))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    cell.append((cx, cy, ms * np.sqrt(ar), ms / np.sqrt(ar)))
            else:
                for ar in ars:
                    cell.append((cx, cy, ms * np.sqrt(ar), ms / np.sqrt(ar)))
                if max_sizes:
                    big = np.sqrt(ms * float(max_sizes[si]))
                    cell.append((cx, cy, big, big))
        for cx_, cy_, bw, bh in cell:
            box = [(cx_ - bw / 2) / iw, (cy_ - bh / 2) / ih,
                   (cx_ + bw / 2) / iw, (cy_ + bh / 2) / ih]
            if clip:
                box = [min(max(v, 0.0), 1.0) for v in box]
            boxes.append(box)
            variances.append(list(variance))
    n_per_cell = len(boxes) // (H * W)
    out = jnp.asarray(boxes, jnp.float32).reshape(H, W, n_per_cell, 4)
    var = jnp.asarray(variances, jnp.float32).reshape(H, W, n_per_cell, 4)
    return Tensor(out), Tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head predictions to boxes+scores (reference
    ops.py:277). x [B, na*(5+C), H, W]."""
    xv = _arr(x).astype(jnp.float32)
    imgs = _arr(img_size)
    B, _, H, W = xv.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    p = xv.reshape(B, na, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (sig(p[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / W
    by = (sig(p[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / H
    bw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] / (
        W * downsample_ratio)
    bh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] / (
        H * downsample_ratio)
    conf = sig(p[:, :, 4])
    cls = sig(p[:, :, 5:])
    score = conf[:, :, None] * cls
    ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
    iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * iw
    y1 = (by - bh / 2) * ih
    x2 = (bx + bw / 2) * iw
    y2 = (by + bh / 2) * ih
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, iw - 1)
        y2 = jnp.minimum(y2, ih - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(B, -1, 4)
    scores = score.transpose(0, 1, 3, 4, 2).reshape(B, -1, class_num)
    keep = conf.reshape(B, -1) > conf_thresh
    boxes = boxes * keep[..., None]
    scores = scores * keep[..., None]
    return Tensor(boxes), Tensor(scores)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference ops.py:766 over
    deformable_conv_kernel.cu): bilinear-sample the input at
    offset-shifted tap positions, contract with the kernel via einsum —
    the MXU does the contraction, no im2col scratch.

    x [B,Cin,H,W]; offset [B, 2*dg*kh*kw, Ho, Wo]; mask (v2)
    [B, dg*kh*kw, Ho, Wo]; weight [Cout, Cin/groups, kh, kw]."""
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1: compose "
                                  "multiple deform_conv2d calls")
    xv = _arr(x)
    off = _arr(offset).astype(jnp.float32)
    w = _arr(weight)
    B, Cin, H, W = xv.shape
    Cout, _, kh, kw = w.shape
    st, pa, di = ((stride, stride) if isinstance(stride, int) else stride,
                  (padding, padding) if isinstance(padding, int) else padding,
                  (dilation, dilation) if isinstance(dilation, int)
                  else dilation)
    Ho = (H + 2 * pa[0] - di[0] * (kh - 1) - 1) // st[0] + 1
    Wo = (W + 2 * pa[1] - di[1] * (kw - 1) - 1) // st[1] + 1
    off = off.reshape(B, kh * kw, 2, Ho, Wo)
    m = (None if mask is None
         else _arr(mask).astype(jnp.float32).reshape(B, kh * kw, Ho, Wo))

    oy = jnp.arange(Ho, dtype=jnp.float32)[:, None] * st[0] - pa[0]
    ox = jnp.arange(Wo, dtype=jnp.float32)[None, :] * st[1] - pa[1]

    def one_image(img, offs, mk):
        cols = []
        for ki in range(kh):
            for kj in range(kw):
                t = ki * kw + kj
                yy = oy + ki * di[0] + offs[t, 0]
                xx = ox + kj * di[1] + offs[t, 1]
                s = _bilinear_sample(img, yy, xx)      # [Cin, Ho, Wo]
                cols.append(s * mk[t])
        col = jnp.stack(cols)                          # [T, Cin, Ho, Wo]
        wk = w.reshape(Cout, Cin, kh * kw)             # [Cout, Cin, T]
        return jnp.einsum("tchw,oct->ohw", col, wk)

    out = jax.vmap(one_image)(xv, off,
                              m if m is not None
                              else jnp.ones((B, kh * kw, Ho, Wo),
                                            jnp.float32))
    if bias is not None:
        out = out + _arr(bias)[None, :, None, None]
    return Tensor(out)


class DeformConv2D(nn.Layer):
    """reference ops.py:973."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self._args = (stride, padding, dilation, deformable_groups, groups)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + k)
        self.bias = (None if bias_attr is False else
                     self.create_parameter((out_channels,), is_bias=True))

    def forward(self, x, offset, mask=None):
        st, pa, di, dg, g = self._args
        return deform_conv2d(x, offset, self.weight, self.bias, st, pa, di,
                             dg, g, mask)


class RoIAlign(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, *self._args)


class RoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, *self._args)


class PSRoIPool(nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, *self._args)


class ConvNormActivation(nn.Sequential):
    """reference ops.py:1877 (torchvision-style building block)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=nn.BatchNorm2D,
                 activation_layer=nn.ReLU, dilation=1, bias=None):
        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if bias is None:
            bias = norm_layer is None
        layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                            padding, dilation=dilation, groups=groups,
                            bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (reference ops.py:1175):
    level = floor(refer_level + log2(sqrt(area)/refer_scale))."""
    rois = np.asarray(_arr(fpn_rois), np.float64)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
                    * np.maximum(rois[:, 3] - rois[:, 1] + off, 0))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    n_levels = max_level - min_level + 1
    multi_rois, restore = [], np.zeros(len(rois), np.int32)
    rois_num_per = []
    cursor = 0
    for li in range(n_levels):
        idx = np.nonzero(lvl == min_level + li)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx], jnp.float32)))
        restore[idx] = np.arange(cursor, cursor + len(idx))
        rois_num_per.append(Tensor(jnp.asarray([len(idx)], jnp.int32)))
        cursor += len(idx)
    restore_t = Tensor(jnp.asarray(restore[:, None], jnp.int32))
    if rois_num is not None:
        return multi_rois, restore_t, rois_num_per
    return multi_rois, restore_t


def read_file(*a, **k):
    raise NotImplementedError(
        "read_file/decode_jpeg are host file IO (reference: CPU-only "
        "kernels); use PIL/numpy and paddle_tpu.to_tensor")


decode_jpeg = read_file


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference ops.py:69 over phi yolov3_loss kernel):
    coordinate + objectness + class terms with responsible-anchor
    assignment and ignore-region masking. Registered through the op
    registry so the eager tape differentiates it.

    TPU shape: target assignment is a static einsum/argmax program over
    [B, n_gt, na] IoU tables — no per-box host loops; the whole loss
    jits. x [B, mask_na*(5+C), H, W]; gt_box [B, n_gt, 4] (x, y, w, h,
    normalized); gt_label [B, n_gt]."""
    from ..ops.registry import call_op

    def impl(xv, gtb, gtl, gts):
        return _yolo_loss_impl(xv, gtb, gtl, gts, anchors, anchor_mask,
                               class_num, ignore_thresh, downsample_ratio,
                               use_label_smooth, scale_x_y)

    gs = gt_score if gt_score is not None else 1
    return call_op("yolo_loss", impl, (x, gt_box, gt_label, gs), {})


def _yolo_loss_impl(xv, gtb, gtl, gts, anchors, anchor_mask, class_num,
                    ignore_thresh, downsample_ratio, use_label_smooth,
                    scale_x_y):
    xv = jnp.asarray(xv, jnp.float32)
    gtb = jnp.asarray(gtb, jnp.float32)
    gtl = jnp.asarray(gtl, jnp.int32)
    gt_score = None if (isinstance(gts, int) and gts == 1) else gts
    B, _, H, W = xv.shape
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    amask = list(anchor_mask)
    na = len(amask)
    an = an_all[jnp.asarray(amask)]
    p = xv.reshape(B, na, 5 + class_num, H, W)
    input_size = downsample_ratio * H

    valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)          # [B, n_gt]

    # --- responsible anchor per gt: best IoU of (0,0)-centered boxes
    # against ALL anchors (reference semantics); the gt belongs to this
    # head only when that anchor is in anchor_mask
    gw = gtb[..., 2] * input_size
    gh = gtb[..., 3] * input_size
    inter = (jnp.minimum(gw[..., None], an_all[None, None, :, 0])
             * jnp.minimum(gh[..., None], an_all[None, None, :, 1]))
    union = (gw * gh)[..., None] + (an_all[:, 0] * an_all[:, 1]
                                    )[None, None, :] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [B,n_gt]
    mask_arr = jnp.asarray(amask)
    local_a = jnp.argmax((best[..., None] == mask_arr[None, None, :])
                         .astype(jnp.int32), axis=-1)
    resp = valid & (best[..., None] == mask_arr[None, None, :]).any(-1)

    gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
    # non-responsible (incl. zero-padded) gts must not scatter at all:
    # route them out of bounds and let mode="drop" discard the update —
    # otherwise a padded box writes zeros over a real target at (0,0,0)
    gi = jnp.where(resp, gi, W)
    gj = jnp.where(resp, gj, H)

    # --- build dense targets by scatter over gt boxes
    obj_tgt = jnp.zeros((B, na, H, W))
    tx = jnp.zeros((B, na, H, W))
    ty = jnp.zeros((B, na, H, W))
    tw = jnp.zeros((B, na, H, W))
    th = jnp.zeros((B, na, H, W))
    tcls = jnp.zeros((B, na, class_num, H, W))
    tscale = jnp.zeros((B, na, H, W))
    bidx = jnp.broadcast_to(jnp.arange(B)[:, None], gi.shape)
    sw = jnp.where(resp, 1.0, 0.0)
    score = (jnp.where(resp, jnp.asarray(gt_score, jnp.float32), 0.0)
             if gt_score is not None else sw)
    obj_tgt = obj_tgt.at[bidx, local_a, gj, gi].max(score, mode="drop")
    tx = tx.at[bidx, local_a, gj, gi].set(
        jnp.where(resp, gtb[..., 0] * W - gi, 0.0), mode="drop")
    ty = ty.at[bidx, local_a, gj, gi].set(
        jnp.where(resp, gtb[..., 1] * H - gj, 0.0), mode="drop")
    tw = tw.at[bidx, local_a, gj, gi].set(jnp.where(
        resp, jnp.log(jnp.maximum(gw / jnp.maximum(an[local_a][..., 0],
                                                   1e-10), 1e-9)), 0.0), mode="drop")
    th = th.at[bidx, local_a, gj, gi].set(jnp.where(
        resp, jnp.log(jnp.maximum(gh / jnp.maximum(an[local_a][..., 1],
                                                   1e-10), 1e-9)), 0.0), mode="drop")
    tscale = tscale.at[bidx, local_a, gj, gi].set(
        jnp.where(resp, 2.0 - gtb[..., 2] * gtb[..., 3], 0.0), mode="drop")
    smooth = (1.0 / max(class_num, 1) if use_label_smooth and class_num > 1
              else 0.0)
    onehot = jax.nn.one_hot(gtl, class_num) * (1 - smooth) + smooth / 2
    tcls = tcls.at[bidx, local_a, :, gj, gi].set(
        jnp.where(resp[..., None], onehot, 0.0), mode="drop")

    # --- ignore mask: predictions overlapping any gt above threshold
    sig = jax.nn.sigmoid
    gx_grid = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy_grid = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    px = (sig(p[:, :, 0]) + gx_grid) / W
    py = (sig(p[:, :, 1]) + gy_grid) / H
    pw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] / input_size
    ph = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] / input_size
    pb = jnp.stack([px - pw / 2, py - ph / 2, px + pw / 2, py + ph / 2], -1)
    gb = jnp.stack([gtb[..., 0] - gtb[..., 2] / 2,
                    gtb[..., 1] - gtb[..., 3] / 2,
                    gtb[..., 0] + gtb[..., 2] / 2,
                    gtb[..., 1] + gtb[..., 3] / 2], -1)  # [B, n_gt, 4]
    lt = jnp.maximum(pb[..., None, :2], gb[:, None, None, None, :, :2])
    rb = jnp.minimum(pb[..., None, 2:], gb[:, None, None, None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter2 = wh[..., 0] * wh[..., 1]
    area_p = jnp.maximum((pb[..., 2] - pb[..., 0])
                         * (pb[..., 3] - pb[..., 1]), 0)
    area_g = jnp.maximum((gb[..., 2] - gb[..., 0])
                         * (gb[..., 3] - gb[..., 1]), 0)
    iou = inter2 / jnp.maximum(
        area_p[..., None] + area_g[:, None, None, None, :] - inter2, 1e-10)
    iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
    ignore = (iou.max(-1) > ignore_thresh) & (obj_tgt <= 0)

    # --- loss terms (bce = sigmoid cross entropy)
    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))

    loss_xy = tscale * obj_tgt * (bce(p[:, :, 0], tx) + bce(p[:, :, 1], ty))
    loss_wh = 0.5 * tscale * obj_tgt * ((p[:, :, 2] - tw) ** 2
                                        + (p[:, :, 3] - th) ** 2)
    obj_logit = p[:, :, 4]
    loss_obj = (obj_tgt * bce(obj_logit, jnp.ones_like(obj_tgt))
                + jnp.where(ignore, 0.0, 1.0) * (1 - obj_tgt)
                * bce(obj_logit, jnp.zeros_like(obj_tgt)))
    loss_cls = obj_tgt[:, :, None] * bce(p[:, :, 5:], tcls)
    return (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3))
            + loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3, 4)))


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference ops.py:2106 over phi
    generate_proposals kernel): decode anchor deltas, clip, filter
    small, NMS per image. scores [B, A, H, W]; bbox_deltas [B, 4A, H, W];
    anchors [H, W, A, 4]; variances like anchors."""
    sc = _arr(scores).astype(jnp.float32)
    deltas = _arr(bbox_deltas).astype(jnp.float32)
    anc = _arr(anchors).astype(jnp.float32).reshape(-1, 4)
    var = _arr(variances).astype(jnp.float32).reshape(-1, 4)
    imgs = _arr(img_size).astype(jnp.float32)
    B, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0

    rois_out, num_out, scores_out = [], [], []
    for b in range(B):
        s = sc[b].transpose(1, 2, 0).reshape(-1)            # [H*W*A]
        d = deltas[b].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        # decode (variance-scaled center-size)
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        dv = d * var
        cx = dv[:, 0] * aw + acx
        cy = dv[:, 1] * ah + acy
        wpred = jnp.exp(jnp.clip(dv[:, 2], -10, 10)) * aw
        hpred = jnp.exp(jnp.clip(dv[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - wpred / 2, cy - hpred / 2,
                           cx + wpred / 2 - off, cy + hpred / 2 - off], -1)
        ih, iw = imgs[b, 0], imgs[b, 1]
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, iw - off),
                           jnp.clip(boxes[:, 1], 0, ih - off),
                           jnp.clip(boxes[:, 2], 0, iw - off),
                           jnp.clip(boxes[:, 3], 0, ih - off)], -1)
        keep_size = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                     & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        s = jnp.where(keep_size, s, -jnp.inf)
        top = min(pre_nms_top_n, s.shape[0])
        order = jnp.argsort(-s)[:top]
        cand_boxes = np.asarray(boxes[order])
        cand_scores = np.asarray(s[order])
        ok = np.isfinite(cand_scores)
        cand_boxes, cand_scores = cand_boxes[ok], cand_scores[ok]
        keep = np.asarray(nms(cand_boxes, nms_thresh,
                              scores=cand_scores).data)[:post_nms_top_n]
        rois_out.append(cand_boxes[keep])
        scores_out.append(cand_scores[keep][:, None])
        num_out.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(rois_out, 0)))
    rscores = Tensor(jnp.asarray(np.concatenate(scores_out, 0)))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(num_out, jnp.int32))
    return rois, rscores

"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST,
Cifar10/100, FashionMNIST, Flowers, VOC2012...).

This environment has zero egress, so datasets load from local files when
present (same on-disk formats as the reference's cached downloads) and
raise a clear error otherwise. ``FakeData`` provides deterministic
synthetic samples for tests/benchmarks (the pattern the reference's CI
uses for dataset-independent model tests).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional, Tuple

import numpy as np

from ..io.dataset import Dataset

_DEFAULT_ROOT = os.path.expanduser("~/.cache/paddle_tpu/datasets")


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples: int = 256,
                 image_shape: Tuple[int, ...] = (3, 32, 32),
                 num_classes: int = 10, transform: Optional[Callable] = None,
                 seed: int = 0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.rand(
            num_samples, *self.image_shape).astype(np.float32)
        self._labels = self._rng.randint(
            0, num_classes, (num_samples, 1)).astype(np.int64)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self._labels[idx]


class MNIST(Dataset):
    """MNIST from local idx-format files (image_path/label_path or the
    standard files under ``root``)."""

    NAME = "mnist"
    _FILES = {
        "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = True, backend: str = "cv2",
                 root: Optional[str] = None):
        root = root or os.path.join(_DEFAULT_ROOT, self.NAME)
        img_f, lbl_f = self._FILES[mode]
        image_path = image_path or os.path.join(root, img_f)
        label_path = label_path or os.path.join(root, lbl_f)
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"{self.NAME} files not found at {image_path} / {label_path}"
                " — this environment has no network access; place the "
                "standard idx files there, or use vision.datasets.FakeData")
        self.transform = transform
        self.images = self._read_idx(image_path, 3)
        self.labels = self._read_idx(label_path, 1).astype(np.int64)

    @staticmethod
    def _read_idx(path: str, ndim: int) -> np.ndarray:
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        dims = struct.unpack_from(f">{ndim}i", data, 4)
        return np.frombuffer(
            data, np.uint8, offset=4 + 4 * ndim).reshape(dims)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]], np.int64)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 from the standard python-pickle tarball under ``root``."""

    _TAR = "cifar-10-python.tar.gz"
    _COARSE = False

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = True,
                 backend: str = "cv2", root: Optional[str] = None):
        root = root or os.path.join(_DEFAULT_ROOT, "cifar")
        data_file = data_file or os.path.join(root, self._TAR)
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"cifar tarball not found at {data_file} — no network "
                "access; place it there or use vision.datasets.FakeData")
        self.transform = transform
        self.images, self.labels = self._load(data_file, mode)

    def _load(self, path, mode):
        imgs, lbls = [], []
        want = "data_batch" if mode == "train" else "test_batch"
        with tarfile.open(path) as tar:
            for m in tar.getmembers():
                if want in m.name:
                    d = pickle.loads(tar.extractfile(m).read(),
                                     encoding="bytes")
                    imgs.append(d[b"data"])
                    lbls.extend(d.get(b"labels", d.get(b"fine_labels")))
        x = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        return x, np.asarray(lbls, np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]], np.int64)


class Cifar100(Cifar10):
    _TAR = "cifar-100-python.tar.gz"

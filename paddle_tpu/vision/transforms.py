"""Image transforms (reference: python/paddle/vision/transforms/transforms.py).

Numpy-based host-side preprocessing: transforms run in DataLoader workers on
CPU; only the collated batch is device_put to TPU. Images are HWC uint8/float
numpy arrays (or CHW float after ToTensor), matching the reference's
conventions.
"""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence, Tuple

import numpy as np


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def _size2(size) -> Tuple[int, int]:
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize via separable linear interpolation (no PIL/cv2
    dependency in this environment)."""
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    ys = np.linspace(0, ih - 1, h)
    xs = np.linspace(0, iw - 1, w)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    im = img.astype(np.float32)
    if im.ndim == 2:
        im = im[:, :, None]
    top = im[y0][:, x0] * (1 - wx[..., None]) + im[y0][:, x1] * wx[..., None]
    bot = im[y1][:, x0] * (1 - wx[..., None]) + im[y1][:, x1] * wx[..., None]
    out = top * (1 - wy[..., None]) + bot * wy[..., None]
    if img.ndim == 2:
        out = out[:, :, 0]
    return out.astype(img.dtype) if img.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def _apply_image(self, img):
        if isinstance(self.size, numbers.Number):
            # shorter side -> size, keep aspect
            h, w = img.shape[:2]
            if h < w:
                nh, nw = int(self.size), int(round(w * self.size / h))
            else:
                nh, nw = int(round(h * self.size / w)), int(self.size)
        else:
            nh, nw = _size2(self.size)
        return _resize_np(img, nh, nw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = _size2(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = _size2(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else (self.padding,) * 4
            pad = [(p[1], p[3]), (p[0], p[2])] + \
                  [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pad)
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[::-1].copy()
        return img


class Normalize(BaseTransform):
    """(img - mean) / std per channel; expects CHW float (after ToTensor)
    or HWC with data_format='HWC'."""

    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (img - m) / s


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] numpy (collate device_puts)."""

    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        # scale by the ORIGINAL dtype, not the values: integer images are
        # always /255, float images are passed through (deciding by
        # img.max() would scale the same uint8 image differently
        # depending on its content)
        was_int = np.issubdtype(img.dtype, np.integer)
        img = img.astype(np.float32)
        if was_int:
            img = img / 255.0
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        dt = img.dtype
        out = np.clip(img.astype(np.float32) * alpha, 0,
                      255 if dt == np.uint8 else np.inf)
        return out.astype(dt)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.brightness = BrightnessTransform(brightness)

    def _apply_image(self, img):
        return self.brightness(img)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def hflip(img):
    return img[:, ::-1].copy()


def vflip(img):
    return img[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return img[top:top + height, left:left + width]

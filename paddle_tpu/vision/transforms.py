"""Image transforms (reference: python/paddle/vision/transforms/transforms.py).

Numpy-based host-side preprocessing: transforms run in DataLoader workers on
CPU; only the collated batch is device_put to TPU. Images are HWC uint8/float
numpy arrays (or CHW float after ToTensor), matching the reference's
conventions.
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence, Tuple

import numpy as np


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def _size2(size) -> Tuple[int, int]:
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize via separable linear interpolation (no PIL/cv2
    dependency in this environment)."""
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    ys = np.linspace(0, ih - 1, h)
    xs = np.linspace(0, iw - 1, w)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, ih - 1)
    x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    im = img.astype(np.float32)
    if im.ndim == 2:
        im = im[:, :, None]
    top = im[y0][:, x0] * (1 - wx[..., None]) + im[y0][:, x1] * wx[..., None]
    bot = im[y1][:, x0] * (1 - wx[..., None]) + im[y1][:, x1] * wx[..., None]
    out = top * (1 - wy[..., None]) + bot * wy[..., None]
    if img.ndim == 2:
        out = out[:, :, 0]
    return out.astype(img.dtype) if img.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def _apply_image(self, img):
        if isinstance(self.size, numbers.Number):
            # shorter side -> size, keep aspect
            h, w = img.shape[:2]
            if h < w:
                nh, nw = int(self.size), int(round(w * self.size / h))
            else:
                nh, nw = int(round(h * self.size / w)), int(self.size)
        else:
            nh, nw = _size2(self.size)
        return _resize_np(img, nh, nw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = _size2(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = _size2(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else (self.padding,) * 4
            pad = [(p[1], p[3]), (p[0], p[2])] + \
                  [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pad)
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return img[::-1].copy()
        return img


class Normalize(BaseTransform):
    """(img - mean) / std per channel; expects CHW float (after ToTensor)
    or HWC with data_format='HWC'."""

    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        return (img - m) / s


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] numpy (collate device_puts)."""

    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        # scale by the ORIGINAL dtype, not the values: integer images are
        # always /255, float images are passed through (deciding by
        # img.max() would scale the same uint8 image differently
        # depending on its content)
        was_int = np.issubdtype(img.dtype, np.integer)
        img = img.astype(np.float32)
        if was_int:
            img = img / 255.0
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        dt = img.dtype
        out = np.clip(img.astype(np.float32) * alpha, 0,
                      255 if dt == np.uint8 else np.inf)
        return out.astype(dt)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.brightness = BrightnessTransform(brightness)
        self._cfg = (contrast, saturation, hue)

    def _apply_image(self, img):
        img = self.brightness(img)
        contrast, saturation, hue = self._cfg
        order = np.random.permutation(3)
        for which in order:
            if which == 0 and contrast:
                img = ContrastTransform(contrast)(img)
            elif which == 1 and saturation:
                img = SaturationTransform(saturation)(img)
            elif which == 2 and hue:
                img = HueTransform(hue)(img)
        return img


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def hflip(img):
    return img[:, ::-1].copy()


def vflip(img):
    return img[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return img[top:top + height, left:left + width]


# -- functional image ops (reference vision/transforms/functional.py) -------

def adjust_brightness(img, brightness_factor):
    dt = img.dtype
    hi = 255 if dt == np.uint8 else np.inf
    return np.clip(img.astype(np.float32) * brightness_factor, 0,
                   hi).astype(dt)


def adjust_contrast(img, contrast_factor):
    dt = img.dtype
    gray = _rgb_to_gray(img).mean()
    hi = 255 if dt == np.uint8 else np.inf
    out = gray + contrast_factor * (img.astype(np.float32) - gray)
    return np.clip(out, 0, hi).astype(dt)


def _rgb_to_gray(img):
    im = img.astype(np.float32)
    if im.ndim == 2 or im.shape[-1] == 1:
        return im.reshape(im.shape[:2])
    return im[..., 0] * 0.299 + im[..., 1] * 0.587 + im[..., 2] * 0.114


def adjust_saturation(img, saturation_factor):
    dt = img.dtype
    gray = _rgb_to_gray(img)[..., None]
    hi = 255 if dt == np.uint8 else np.inf
    out = gray + saturation_factor * (img.astype(np.float32) - gray)
    return np.clip(out, 0, hi).astype(dt)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV round trip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    dt = img.dtype
    im = img.astype(np.float32) / (255.0 if dt == np.uint8 else 1.0)
    mx = im.max(-1)
    mn = im.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = im[..., 0], im[..., 1], im[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    # hsv -> rgb
    i = np.floor(h * 6).astype(np.int64) % 6
    f = h * 6 - np.floor(h * 6)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    choices = np.stack([
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)], 0)
    out = np.take_along_axis(choices, i[None, ..., None], axis=0)[0]
    out = out * (255.0 if dt == np.uint8 else 1.0)
    return np.clip(out, 0, 255 if dt == np.uint8 else np.inf).astype(dt)


def to_grayscale(img, num_output_channels=1):
    g = _rgb_to_gray(img)
    out = np.repeat(g[..., None], num_output_channels, axis=-1)
    return out.astype(img.dtype) if img.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, numbers.Number):
        l = r = t = b = int(padding)
    elif len(padding) == 2:
        l, t = padding
        r, b = padding
    else:
        l, t, r, b = padding
    widths = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
    if padding_mode == "constant":
        return np.pad(img, widths, constant_values=fill)
    return np.pad(img, widths, mode={"edge": "edge", "reflect": "reflect",
                                     "symmetric": "symmetric"}[padding_mode])


def erase(img, i, j, h, w, v, inplace=False):
    out = img if inplace else img.copy()
    chw = out.ndim == 3 and out.shape[0] in (1, 3) and out.shape[-1] not in (1, 3)
    if chw:
        out[:, i:i + h, j:j + w] = v
    else:
        out[i:i + h, j:j + w] = v
    return out


def _warp(img, minv, fill=0, out_size=None, interpolation="bilinear"):
    """Inverse-map warp with bilinear/nearest sampling; minv maps OUTPUT
    (x, y) homogeneous coords to INPUT coords. out_size=(oh, ow) sets the
    output canvas (defaults to the input's)."""
    ih, iw = img.shape[:2]
    oh, ow = out_size if out_size is not None else (ih, iw)
    ys, xs = np.mgrid[0:oh, 0:ow].astype(np.float32)
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)
    src = minv @ coords
    sx = (src[0] / src[2]).reshape(oh, ow)
    sy = (src[1] / src[2]).reshape(oh, ow)
    if interpolation == "nearest":
        # floor(x+0.5), not np.round: banker's rounding combs half-pixel
        # coords (PIL/cv2 nearest round half up)
        sx, sy = np.floor(sx + 0.5), np.floor(sy + 0.5)
    elif interpolation != "bilinear":
        raise ValueError(
            f"unsupported interpolation {interpolation!r}: this build "
            "implements 'nearest' and 'bilinear'")
    x0 = np.floor(sx)
    y0 = np.floor(sy)
    lx, ly = sx - x0, sy - y0
    im = img.astype(np.float32)
    if im.ndim == 2:
        im = im[:, :, None]
    out = np.zeros((oh, ow, im.shape[2]), np.float32)
    for dy, wy in ((0, 1 - ly), (1, ly)):
        for dx, wx in ((0, 1 - lx), (1, lx)):
            xi = x0 + dx
            yi = y0 + dy
            ok = (xi >= 0) & (xi < iw) & (yi >= 0) & (yi < ih)
            xi = np.clip(xi, 0, iw - 1).astype(np.int64)
            yi = np.clip(yi, 0, ih - 1).astype(np.int64)
            w = (wy * wx * ok)[..., None]
            out += np.where(ok[..., None], im[yi, xi], fill) * w
    oob = (sx < -0.5) | (sx > iw - 0.5) | (sy < -0.5) | (sy > ih - 0.5)
    out[oob] = fill
    if img.ndim == 2:
        out = out[..., 0]
    return out.astype(img.dtype) if img.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


def _affine_fwd_matrix(angle, translate, scale, shear, center):
    """Forward map for a CLOCKWISE ``angle`` (the affine() convention;
    reference functional.py:642)."""
    a = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Sh T(-center) + translate
    rot = np.asarray([[np.cos(a + sy), -np.sin(a + sx), 0],
                      [np.sin(a + sy), np.cos(a + sx), 0],
                      [0, 0, 1]], np.float64)
    sc = np.diag([scale, scale, 1.0])
    to_c = np.asarray([[1, 0, cx], [0, 1, cy], [0, 0, 1]], np.float64)
    from_c = np.asarray([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float64)
    tr = np.asarray([[1, 0, tx], [0, 1, ty], [0, 0, 1]], np.float64)
    return tr @ to_c @ rot @ sc @ from_c


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    h, w = img.shape[:2]
    center = center or ((w - 1) / 2.0, (h - 1) / 2.0)
    shear = shear if isinstance(shear, (list, tuple)) else (shear, 0.0)
    fwd = _affine_fwd_matrix(angle, translate, scale, shear, center)
    return _warp(img, np.linalg.inv(fwd), fill,
                 interpolation=interpolation)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    # angle is COUNTER-clockwise (reference functional.py:778), the
    # opposite of affine()'s clockwise convention
    h, w = img.shape[:2]
    center = center or ((w - 1) / 2.0, (h - 1) / 2.0)
    fwd = _affine_fwd_matrix(-angle, (0, 0), 1.0, (0.0, 0.0), center)
    if not expand:
        return _warp(img, np.linalg.inv(fwd), fill,
                     interpolation=interpolation)
    # expand: canvas grows to the rotated image's bounding box
    corners = np.asarray([[0, 0, 1], [w - 1, 0, 1],
                          [0, h - 1, 1], [w - 1, h - 1, 1]], np.float64).T
    mapped = fwd @ corners
    cx, cy = mapped[0] / mapped[2], mapped[1] / mapped[2]
    ow = int(np.ceil(cx.max() - cx.min())) + 1
    oh = int(np.ceil(cy.max() - cy.min())) + 1
    shift = np.asarray([[1, 0, cx.min()], [0, 1, cy.min()], [0, 0, 1]],
                       np.float64)
    return _warp(img, np.linalg.inv(fwd) @ shift, fill, out_size=(oh, ow),
                 interpolation=interpolation)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """Warp mapping startpoints -> endpoints (4 corner pairs)."""
    A = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        A.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
    b = np.asarray([c for pt in endpoints for c in pt], np.float64)
    coef = np.linalg.lstsq(np.asarray(A, np.float64), b, rcond=None)[0]
    fwd = np.append(coef, 1.0).reshape(3, 3)
    return _warp(img, np.linalg.inv(fwd), fill, interpolation=interpolation)


# -- class transforms -------------------------------------------------------

class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_contrast(img,
                               1 + np.random.uniform(-self.value, self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_saturation(
            img, 1 + np.random.uniform(-self.value, self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.args = (padding, fill, padding_mode)

    def _apply_image(self, img):
        return pad(img, *self.args)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        self.degrees = (degrees if isinstance(degrees, (list, tuple))
                        else (-degrees, degrees))
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, interpolation=self.interpolation,
                      expand=self.expand, center=self.center,
                      fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = (degrees if isinstance(degrees, (list, tuple))
                        else (-degrees, degrees))
        self.translate = translate
        self.scale = scale
        if shear is not None and not isinstance(shear, (list, tuple)):
            shear = (-shear, shear)
        if shear is not None and len(shear) not in (2, 4):
            raise ValueError("shear must be a number or a 2- or 4-element "
                             f"sequence, got {shear!r}")
        self.shear = shear  # 2 elems: x-range; 4: x-range + y-range
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        h, w = img.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = (np.random.uniform(*self.scale) if self.scale else 1.0)
        sh_x = sh_y = 0.0
        if self.shear is not None:
            sh_x = np.random.uniform(self.shear[0], self.shear[1])
            if len(self.shear) == 4:
                sh_y = np.random.uniform(self.shear[2], self.shear[3])
        return affine(img, angle=angle, translate=(tx, ty), scale=sc,
                      shear=(sh_x, sh_y), interpolation=self.interpolation,
                      fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0, keys=None):
        self.prob = prob
        self.scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = img.shape[:2]
        d = self.scale
        tl = (np.random.uniform(0, d * w / 2), np.random.uniform(0, d * h / 2))
        tr = (w - 1 - np.random.uniform(0, d * w / 2),
              np.random.uniform(0, d * h / 2))
        br = (w - 1 - np.random.uniform(0, d * w / 2),
              h - 1 - np.random.uniform(0, d * h / 2))
        bl = (np.random.uniform(0, d * w / 2),
              h - 1 - np.random.uniform(0, d * h / 2))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return perspective(img, start, [tl, tr, br, bl],
                           interpolation=self.interpolation, fill=self.fill)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation="bilinear", keys=None):
        self.size = _size2(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = img[top:top + ch, left:left + cw]
                return _resize_np(patch, *self.size)
        side = min(h, w)  # fallback: center crop
        top, left = (h - side) // 2, (w - side) // 2
        return _resize_np(img[top:top + side, left:left + side], *self.size)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = (img.shape[1:3] if img.ndim == 3 and img.shape[0] in (1, 3)
                and img.shape[-1] not in (1, 3) else img.shape[:2])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value)
        return img

"""vision.models — re-export of the model zoo under the reference's path
(python/paddle/vision/models/__init__.py)."""
from ...models.lenet import LeNet  # noqa: F401
from ...models.resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock,
    resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2, resnext50_32x4d, resnext101_64x4d,
)

"""vision.models — re-export of the model zoo under the reference's path
(python/paddle/vision/models/__init__.py)."""
from ...models.lenet import LeNet  # noqa: F401
from ...models.resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock,
    resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2, resnext50_32x4d, resnext50_64x4d,
    resnext101_32x4d, resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
)
from ...models.vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from ...models.alexnet import AlexNet, alexnet  # noqa: F401
from ...models.squeezenet import (  # noqa: F401
    SqueezeNet, squeezenet1_0, squeezenet1_1)
from ...models.mobilenet import (  # noqa: F401
    MobileNetV1, MobileNetV2, MobileNetV3Small, MobileNetV3Large,
    mobilenet_v1, mobilenet_v2, mobilenet_v3_small, mobilenet_v3_large)
from ...models.densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
    densenet264)
from ...models.shufflenetv2 import (  # noqa: F401
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_33,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0, shufflenet_v2_swish)
from ...models.googlenet import GoogLeNet, googlenet  # noqa: F401
from ...models.inceptionv3 import InceptionV3, inception_v3  # noqa: F401

"""Span tracer: bounded-ring host spans with Perfetto export.

The runtime counterpart of the static-analysis subsystem's proofs
(ISSUE r13): every serving tick, engine phase and per-request
lifecycle step records a *span* — ``(name, track, t0, t1, args)`` on
the process-shared monotonic clock — into a thread-safe bounded ring.
``export(path)`` writes the ring as Chrome-trace JSON ("trace events"
format), loadable in Perfetto / chrome://tracing: one track per engine
phase and one per serving slot, so a slow tick, a TTFT spike or a
mid-run compile is *visible* as geometry on a timeline instead of a
p99 in a histogram.

Design constraints, in order:

* **cheap when on** — a span append is one ``monotonic_ns`` pair, one
  small object and one deque append under a lock (the serving engine's
  measured tracing overhead is pinned ≤ 3% of tick wall by a slow
  test, see docs/OBSERVABILITY.md);
* **near-free when off** — ``enabled=False`` makes ``span()`` record
  nothing (no clock reads, no ring append); only the thread-local
  span-name push/pop survives, so the recompile sentinel's "compile
  during <span>" attribution stays correct with tracing disabled;
* **never unbounded** — the ring is a ``deque(maxlen=capacity)``;
  old spans fall off, ``dropped`` counts them. A serving process can
  trace forever and export the recent window on demand (the flight
  recorder rides the same ring for postmortems);
* **one clock** — ``time.monotonic()`` everywhere, the clock the
  serving ``Request`` timestamps (submit/admit/first-token) already
  use, so retroactive spans (queue wait, TTFT) are *exactly* the
  histogram observations and the two views reconcile by construction.

The innermost open span of each thread is published module-wide
(``current_span()``): the recompile sentinel names compile events
after the span they interrupted ("compile during serving.tick").
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Span", "SpanTracer", "current_span"]

_tls = threading.local()


def _span_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional[str]:
    """Name of this thread's innermost OPEN span (None outside any).
    The recompile sentinel uses this to name what a compile event
    interrupted."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class Span:
    """One closed span. Timestamps are ``time.monotonic()`` ns."""

    __slots__ = ("name", "track", "t0", "t1", "args", "tid")

    def __init__(self, name: str, track: str, t0: int, t1: int,
                 args: Optional[dict], tid: int):
        self.name = name
        self.track = track
        self.t0 = t0
        self.t1 = t1
        self.args = args
        self.tid = tid

    @property
    def dur_s(self) -> float:
        return (self.t1 - self.t0) / 1e9

    def to_dict(self) -> dict:
        d = {"name": self.name, "track": self.track,
             "t0_s": self.t0 / 1e9, "dur_s": self.dur_s}
        if self.args:
            d["args"] = self.args
        return d


class _StackOnlyCtx:
    """Disabled-tracer span: maintains the thread-local span-name
    stack (so ``current_span()`` — the recompile sentinel's ``during``
    attribution — keeps working with tracing off) but records nothing:
    no clock reads, no Span allocation, no ring append."""

    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        _span_stack().append(self._name)
        return self

    def __exit__(self, *exc):
        st = _span_stack()
        if st and st[-1] == self._name:
            st.pop()
        return False


class _SpanCtx:
    """Context manager recording one span on exit."""

    __slots__ = ("_tr", "_name", "_track", "_args", "_t0")

    def __init__(self, tr: "SpanTracer", name: str, track: str, args):
        self._tr = tr
        self._name = name
        self._track = track
        self._args = args

    def __enter__(self):
        _span_stack().append(self._name)
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic_ns()
        st = _span_stack()
        if st and st[-1] == self._name:
            st.pop()
        self._tr._append(Span(self._name, self._track, self._t0, t1,
                              self._args, threading.get_ident()))
        return False


class SpanTracer:
    """Thread-safe bounded ring of host spans.

        tr = SpanTracer()
        with tr.span("tick", track="engine.decode", tick=3):
            ...
        tr.add("queue", "slot0", t_submit, t_admit, req=12)  # retroactive
        tr.export("trace.json")       # Perfetto / chrome://tracing
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True):
        self._ring: "deque[Span]" = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.enabled = bool(enabled)
        self.dropped = 0
        self._t_open = time.monotonic_ns()

    # ------------------------------------------------------------ record ----
    def _append(self, span: Span) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)

    def span(self, name: str, track: Optional[str] = None, **args):
        """Timed context manager; ``track`` defaults to the name.
        Disabled tracers still publish the span name to
        ``current_span()`` (sentinel attribution) but record nothing."""
        if not self.enabled:
            return _StackOnlyCtx(name)
        return _SpanCtx(self, name, track or name, args or None)

    def add(self, name: str, track: str, t0_s: float, t1_s: float,
            **args) -> None:
        """Record a span from explicit ``time.monotonic()`` SECONDS
        timestamps (retroactive lifecycle spans: queue wait, TTFT,
        whole-request) — the same clock the serving Request stamps, so
        span durations equal the metric observations exactly."""
        if not self.enabled:
            return
        self._append(Span(name, track, int(t0_s * 1e9), int(t1_s * 1e9),
                          args or None, threading.get_ident()))

    def instant(self, name: str, track: str, **args) -> None:
        """Zero-length marker span (retire/evict/compile events)."""
        if not self.enabled:
            return
        t = time.monotonic_ns()
        self._append(Span(name, track, t, t, args or None,
                          threading.get_ident()))

    # ------------------------------------------------------------ export ----
    def spans(self) -> List[Span]:
        """Snapshot of the ring, oldest first (consistent under
        concurrent appends)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def to_chrome_trace(self) -> dict:
        """The ring as a Chrome-trace ("trace events") dict: one
        Perfetto thread (tid) per distinct track, complete events
        ("ph": "X") with microsecond timestamps, a thread_name metadata
        event per track. Tracks sort engine phases first, then slots."""
        spans = self.spans()
        tracks: Dict[str, int] = {}
        for s in spans:
            if s.track not in tracks:
                tracks[s.track] = 0

        def _order(t: str):
            if t.startswith("engine"):
                return (0, 0, t)
            if t.startswith("slot") and t[4:].isdigit():
                return (2, int(t[4:]), t)   # slot10 after slot9
            return (1, 0, t)

        for i, t in enumerate(sorted(tracks, key=_order)):
            tracks[t] = i + 1
        events = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
                   "args": {"name": "paddle_tpu serving"}}]
        for t, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": tid, "args": {"name": t}})
            events.append({"ph": "M", "name": "thread_sort_index",
                           "pid": 1, "tid": tid,
                           "args": {"sort_index": tid}})
        for s in spans:
            ev = {"ph": "X", "name": s.name, "pid": 1,
                  "tid": tracks[s.track], "ts": s.t0 / 1e3,
                  "dur": max(s.t1 - s.t0, 0) / 1e3, "cat": s.track}
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"clock": "monotonic",
                              "spans": len(spans),
                              "dropped": self.dropped}}

    def export(self, path: str) -> str:
        """Write the ring as Perfetto-loadable Chrome-trace JSON;
        returns ``path``."""
        with open(path, "w") as f:
            # default=str: span args are plain host scalars by
            # convention, but an exotic arg must degrade to its repr,
            # not kill the export
            json.dump(self.to_chrome_trace(), f, default=str)
        return path

"""Live recompile sentinel: the static ≤2-programs proof as an alarm.

The recompile-hazard pass (analysis/recompile.py) *proves* at engine
construction that the ragged serving dispatch reaches 1-2 programs per
packed-width bucket. That proof is about reachable dispatch — it cannot
see a mis-sized warmup, a config drift between blue/green restarts, or
a jax upgrade quietly changing a trace key. Those failures all present
the same way in production: an XLA compile *inside a serving tick*, a
multi-second stall the p99 histogram only reports after the fact.

The sentinel watches the real thing: ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` event fires on every
executable materialization in the process (including persistent-cache
hits — a cache hit is still a program this process had not warmed, so
it still counts; measured on jax 0.4.37). One module-level listener is
registered once and dispatches to every live sentinel:

* before ``arm()`` (warmup), compiles are counted but expected;
* after ``arm()``, every compile is an alarm: a labeled WARN metric
  (``recompiles{during=...}``), a span on the ``sentinel`` track named
  after the innermost open span it interrupted ("compile during
  serving.tick"), and a ``RecompileWarning``.

``report()`` carries the engine's *expected* program inventory
(``analysis.recompile.program_inventory`` — the same schema
``graph_lint --json`` emits in its ``observability`` block), so the
static and runtime views of "what should ever compile here" are one
diffable document.
"""
from __future__ import annotations

import threading
import time
import warnings
import weakref
from collections import deque
from typing import Optional

from .tracer import current_span

__all__ = ["RecompileSentinel", "RecompileWarning", "COMPILE_EVENT",
           "RECOMPILES_METRIC"]

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# the prometheus series the sentinel's alarms land in
# (ServingMetrics.expose: <prefix>_<counter>_total); graph_lint --json
# names the same string in its observability block so CI consumers and
# scrape configs share one source of truth
RECOMPILES_METRIC = "paddle_serving_recompiles_total"


class RecompileWarning(UserWarning):
    """A post-warmup XLA compile was observed by a RecompileSentinel."""


_installed = False
_install_lock = threading.Lock()
# live sentinels; weak so an abandoned engine cannot leak through the
# process-wide listener
_active: "weakref.WeakSet" = weakref.WeakSet()


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    if event != COMPILE_EVENT:
        return
    for s in list(_active):
        s._on_compile(duration)


def _install_listener() -> None:
    global _installed
    with _install_lock:
        if _installed:
            return
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        _installed = True


class RecompileSentinel:
    """Count and name every XLA compile; alarm on any after ``arm()``.

        s = RecompileSentinel(expected=program_inventory(geom),
                              tracer=tr, metrics=m, label="serving")
        ... warmup traffic ...
        s.arm()                      # warmup done: compiles now WARN
        ... serve ...
        s.report()["post_warmup_compiles"]   # 0 when clean

    The listener fires on whichever thread ran the jit call, so the
    event is named after that thread's innermost open tracer span —
    for the serving engine that is the tick span that stalled.
    ``close()`` detaches the sentinel (the process-wide listener stays,
    dispatching to whoever remains).

    Scope note: compile events are PROCESS-wide. A sentinel on an
    otherwise-idle serving process attributes every post-warmup compile
    to serving (the intent); co-resident non-serving jax work shows up
    too and is distinguishable by its ``during`` span name.
    """

    def __init__(self, *, expected: Optional[dict] = None,
                 tracer=None, metrics=None, label: str = "serving",
                 max_events: int = 256):
        self.expected = expected
        self.label = label
        self._tracer = tracer
        self._metrics = metrics
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self.warmup_compiles = 0
        self.post_warmup_compiles = 0
        self.events: "deque[dict]" = deque(maxlen=int(max_events))
        self._closed = False
        _install_listener()
        _active.add(self)

    # ------------------------------------------------------------ state ----
    @property
    def armed(self) -> bool:
        return self._armed_at is not None

    @property
    def clean(self) -> bool:
        """True when no compile has been seen since ``arm()``."""
        return self.post_warmup_compiles == 0

    def arm(self) -> None:
        """Declare warmup complete: every later compile is an alarm.
        Idempotent (re-arming does not forgive earlier alarms)."""
        with self._lock:
            if self._armed_at is None:
                self._armed_at = time.monotonic()

    def close(self) -> None:
        """Stop observing (engine shutdown)."""
        self._closed = True
        _active.discard(self)

    # --------------------------------------------------------- listener ----
    def _on_compile(self, duration: float) -> None:
        if self._closed:
            return
        during = current_span()
        now = time.monotonic()
        with self._lock:
            armed = self._armed_at is not None
            ev = {"t_s": now, "compile_s": float(duration),
                  "during": during,
                  "phase": "post_warmup" if armed else "warmup"}
            self.events.append(ev)
            if not armed:
                self.warmup_compiles += 1
                return
            self.post_warmup_compiles += 1
        name = f"compile during {during}" if during else \
            "compile (no active span)"
        if self._metrics is not None:
            try:
                self._metrics.inc("recompiles")
                self._metrics.inc_labeled(
                    "recompiles", during=during or "idle")
            except Exception:
                pass
        if self._tracer is not None:
            self._tracer.add(name, "sentinel", now - duration, now,
                             compile_s=round(float(duration), 6))
        warnings.warn(
            f"[{self.label}] post-warmup XLA compile "
            f"({duration * 1e3:.1f} ms) — {name}; the one-program-tick "
            f"warmup did not cover this program (see "
            f"docs/OBSERVABILITY.md recompile sentinel)",
            RecompileWarning, stacklevel=2)

    # ------------------------------------------------------------ export ----
    def report(self) -> dict:
        """Plain-dict sentinel state: counts, recent events, the
        expected static program inventory, and ``clean``."""
        with self._lock:
            return {
                "label": self.label,
                "armed": self._armed_at is not None,
                "warmup_compiles": self.warmup_compiles,
                "post_warmup_compiles": self.post_warmup_compiles,
                "clean": self.post_warmup_compiles == 0,
                "expected_programs": self.expected,
                "events": list(self.events),
            }

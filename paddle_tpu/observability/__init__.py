"""paddle_tpu.observability — runtime evidence for the serving stack.

The static-analysis subsystem (paddle_tpu/analysis/) *proves* serving
invariants offline: the recompile pass enumerates the reachable tick
program set, the KV checker audits page ownership, the HBM estimator
bounds peaks. This package is the runtime half (ISSUE r13): the same
invariants *watched while serving*, and the evidence shipped with every
anomaly instead of reconstructed after it.

    SpanTracer       — thread-safe bounded-ring span tracer; Chrome-
                       trace/Perfetto export, one track per engine
                       phase + one per serving slot (tracer.py)
    FlightRecorder   — last-N-ticks ring + JSON postmortem dumped
                       automatically on KVInvariantError / engine-loop
                       crash (flight.py)
    RecompileSentinel— jax.monitoring compile listener: any XLA compile
                       after warmup becomes a labeled WARN metric, a
                       named span and a RecompileWarning, cross-checked
                       against the static program inventory
                       (sentinel.py)

Wired through ``serving.ServingEngine`` (``trace=``, ``flight_ticks=``,
``recompile_sentinel=`` ctor knobs; on by default — measured overhead
≤3% of tick wall, pinned by test) and surfaced by
``tools/serving_bench.py --trace`` / ``--check-invariants`` and
``graph_lint --json``'s ``observability`` block. See
docs/OBSERVABILITY.md.
"""
from .flight import FlightRecorder, default_flight_dir  # noqa: F401
from .sentinel import (COMPILE_EVENT, RECOMPILES_METRIC,  # noqa: F401
                       RecompileSentinel, RecompileWarning)
from .tracer import Span, SpanTracer, current_span  # noqa: F401

__all__ = ["SpanTracer", "Span", "current_span", "FlightRecorder",
           "default_flight_dir", "RecompileSentinel", "RecompileWarning",
           "COMPILE_EVENT", "RECOMPILES_METRIC", "bridge_record_events"]


def bridge_record_events(tracer: SpanTracer, track: str = "profiler"):
    """Mirror every closing ``profiler.RecordEvent`` span into
    ``tracer`` on one ``track`` — device-trace annotations and the
    serving engine's own spans then read in the same Perfetto export.
    Returns a zero-arg detach callable."""
    from .. import profiler

    def _sink(name, t0_s, t1_s):
        tracer.add(name, track, t0_s, t1_s)

    profiler.add_span_sink(_sink)

    def detach():
        profiler.remove_span_sink(_sink)
    return detach

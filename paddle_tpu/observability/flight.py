"""Flight recorder: last-N-ticks ring + crash postmortem dump.

When the serving engine dies — a ``KVInvariantError`` from the per-tick
paged-KV audit, or any unhandled engine-loop exception — the aggregate
histograms say nothing about *which* geometry, program set and recent
tick timeline produced the failure. The flight recorder keeps a small
ring of per-tick records (tick index, duration, packed width, live
slots, span tokens, pool/queue gauges) plus the state snapshots needed
to reconstruct the last moments: scheduler slots/lengths/tables,
PagePool occupancy, PrefixCache stats. ``dump()`` writes one JSON
postmortem combining the ring, the span tracer's recent window, the
metrics snapshot and the error (with the KV-invariant violation list
when that is what killed the engine) — so the offending state ships
WITH the error instead of requiring a reproduction.

The engine calls ``record_tick`` under its tick lock (single writer);
``dump`` may run from the dying worker or from a caller thread, so the
ring is locked anyway. Everything stored is plain
JSON-serializable host data — recording a tick is a dict build and a
deque append, no device sync.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["FlightRecorder", "default_flight_dir"]


def default_flight_dir() -> str:
    """Postmortem directory: ``PADDLE_TPU_FLIGHT_DIR`` env var, else
    ``<tmp>/paddle_tpu_flight``."""
    d = os.environ.get("PADDLE_TPU_FLIGHT_DIR")
    if d:
        return d
    import tempfile
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_flight")


def _jsonable(x):
    """Best-effort plain-data coercion (numpy scalars/arrays, sets)."""
    import numpy as np
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return str(x)


class FlightRecorder:
    """Bounded ring of per-tick serving records + postmortem writer."""

    def __init__(self, capacity: int = 64):
        self._ticks: "deque[dict]" = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.capacity = int(capacity)

    def record_tick(self, **record) -> None:
        """Append one tick record (plain host data only)."""
        with self._lock:
            self._ticks.append(record)

    def ticks(self) -> List[dict]:
        with self._lock:
            return list(self._ticks)

    def dump(self, path: Optional[str] = None, *, error=None,
             dir: Optional[str] = None,
             geometry: Optional[str] = None,
             programs: Optional[dict] = None,
             state: Optional[dict] = None,
             spans: Optional[list] = None,
             metrics: Optional[dict] = None,
             sentinel: Optional[dict] = None) -> str:
        """Write one JSON postmortem; returns the path written.

        ``error`` may be any exception — a ``KVInvariantError``'s
        violation list and context are lifted into structured fields.
        ``path=None`` writes ``postmortem-<pid>-<monotonic_ns>.json``
        under ``dir`` (default :func:`default_flight_dir`), created if
        missing.
        """
        if path is None:
            d = dir or default_flight_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"postmortem-{os.getpid()}-{time.monotonic_ns()}.json")
        doc = {
            "schema": "paddle_tpu.flight_recorder/1",
            "written_unix_s": time.time(),
            "ticks": self.ticks(),
            "tick_ring_capacity": self.capacity,
        }
        if error is not None:
            err = {"type": type(error).__name__, "message": str(error)}
            violations = getattr(error, "violations", None)
            if violations is not None:
                err["violations"] = [
                    {"code": getattr(v, "code", ""),
                     "message": getattr(v, "message", str(v))}
                    for v in violations]
            ctx = getattr(error, "context", None)
            if ctx:
                err["context"] = str(ctx)
            doc["error"] = err
        if geometry is not None:
            doc["geometry"] = geometry
        if programs is not None:
            doc["expected_programs"] = _jsonable(programs)
        if state is not None:
            doc["state"] = _jsonable(state)
        if spans is not None:
            doc["spans"] = spans
        if metrics is not None:
            doc["metrics"] = _jsonable(metrics)
        if sentinel is not None:
            doc["sentinel"] = _jsonable(sentinel)
        with open(path, "w") as f:
            json.dump(_jsonable(doc), f)
        return path

"""Global runtime flag registry.

TPU-native analogue of the reference's exported-flag machinery
(paddle/common/flags.h:337-362 `GetExportedFlagInfoMap`,
PHI_DEFINE_EXPORTED_* macros): a process-wide registry of typed flags,
overridable from the environment as ``FLAGS_<name>`` and from Python via
``get_flags``/``set_flags`` (python/paddle/base/framework.py:132,157 in the
reference).
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional


@dataclass
class FlagInfo:
    name: str
    default: Any
    type: type
    help: str
    value: Any


_FLAGS: Dict[str, FlagInfo] = {}
_LOCK = threading.Lock()


def _parse(tp: type, raw: str):
    if tp is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return tp(raw)


def define_flag(name: str, default, help: str = "", type: Optional[type] = None):
    """Register a flag. Environment variable FLAGS_<name> overrides default."""
    tp = type or (bool if isinstance(default, bool) else default.__class__)
    value = default
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        value = _parse(tp, env)
    with _LOCK:
        _FLAGS[name] = FlagInfo(name, default, tp, help, value)
    return value


def get_flags(flags) -> Dict[str, Any]:
    """Return {name: value} for a flag name or list of names."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = f[6:] if f.startswith("FLAGS_") else f
        if key not in _FLAGS:
            raise ValueError(f"unknown flag: {f}")
        out[f] = _FLAGS[key].value
    return out


def set_flags(flags: Dict[str, Any]):
    """Set flag values from a {name: value} dict."""
    for name, v in flags.items():
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _FLAGS:
            raise ValueError(f"unknown flag: {name}")
        info = _FLAGS[key]
        info.value = _parse(info.type, v) if isinstance(v, str) and info.type is not str else info.type(v)


def get_flag(name: str):
    return _FLAGS[name].value


def all_flags() -> Iterable[FlagInfo]:
    return list(_FLAGS.values())


# Core flags (subset mirroring the reference's most-used ones).
define_flag("check_nan_inf", False, "check op outputs for NaN/Inf after each eager op")
define_flag("default_device", "", "preferred device: 'tpu', 'cpu', or '' for auto")
define_flag("eager_log_ops", False, "log every eager op dispatch (debugging)")
define_flag("amp_dtype", "bfloat16", "low-precision dtype used by amp.auto_cast on TPU")
define_flag("allocator_strategy", "xla", "memory management is delegated to XLA on TPU")
define_flag("jit_static_shapes", True, "pad/bucket dynamic batch shapes in jit capture")
define_flag("use_pallas_kernels", True, "use Pallas kernels for hot ops (flash attention etc.) on TPU")
define_flag("eager_vjp_cache", True, "cache jitted per-op fwd/vjp by (op, shapes, statics) instead of retracing jax.vjp on every eager call")

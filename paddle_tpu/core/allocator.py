"""Host staging allocator + memory stats API.

Reference surface: paddle/phi/core/memory/ (AllocatorFacade,
AutoGrowthBestFitAllocator, stats.h) and the python
paddle.device.cuda.max_memory_allocated family. On TPU, device HBM belongs
to XLA — what the framework owns natively is the pinned host staging
memory the input pipeline uses, managed by the C++ best-fit allocator
(csrc/allocator.cc) when available, with a numpy-backed fallback.
"""
from __future__ import annotations

import ctypes
import threading
from typing import Dict, Optional

import numpy as np

from . import native


class HostAllocator:
    """Auto-growth best-fit host allocator (native when possible)."""

    def __init__(self, chunk_size: int = 64 << 20):
        self._lib = native.lib()
        self._lock = threading.Lock()
        self._py_stats = [0, 0, 0, 0]  # allocated/reserved/peaks fallback
        if self._lib is not None:
            self._h = self._lib.pt_alloc_create(chunk_size)
        else:
            self._h = None
        self._live: Dict[int, object] = {}

    @property
    def native(self) -> bool:
        return self._h is not None

    def alloc_buffer(self, nbytes: int) -> memoryview:
        """A writable buffer of ``nbytes`` from the arena."""
        if self._h is not None:
            ptr = self._lib.pt_alloc_malloc(self._h, nbytes)
            if not ptr:
                raise MemoryError(f"host allocator failed for {nbytes} bytes")
            buf = (ctypes.c_char * nbytes).from_address(ptr)
            mv = memoryview(buf).cast("B")
            with self._lock:
                self._live[id(mv.obj)] = ptr
            return mv
        arr = np.empty(nbytes, np.uint8)
        with self._lock:
            self._py_stats[0] += nbytes
            self._py_stats[1] += nbytes
            self._py_stats[2] = max(self._py_stats[2], self._py_stats[0])
            self._py_stats[3] = max(self._py_stats[3], self._py_stats[1])
            self._live[id(arr)] = arr
        return memoryview(arr)

    def free_buffer(self, mv: memoryview) -> None:
        key = id(mv.obj)
        with self._lock:
            ref = self._live.pop(key, None)
        if ref is None:
            return
        if self._h is not None:
            self._lib.pt_alloc_free(self._h, ref)
        else:
            with self._lock:
                self._py_stats[0] -= mv.nbytes
                self._py_stats[1] -= mv.nbytes

    def stats(self) -> Dict[str, int]:
        if self._h is not None:
            out = (ctypes.c_uint64 * 4)()
            self._lib.pt_alloc_stats(self._h, out)
            vals = list(out)
        else:
            vals = list(self._py_stats)
        return {"allocated": vals[0], "reserved": vals[1],
                "peak_allocated": vals[2], "peak_reserved": vals[3]}

    def reset_peak(self) -> None:
        if self._h is not None:
            self._lib.pt_alloc_reset_peak(self._h)
        else:
            with self._lock:
                self._py_stats[2] = self._py_stats[0]
                self._py_stats[3] = self._py_stats[1]

    def __del__(self):
        if getattr(self, "_h", None) is not None and native.lib() is not None:
            try:
                native.lib().pt_alloc_destroy(self._h)
            except Exception:
                pass


_default: Optional[HostAllocator] = None
_default_lock = threading.Lock()


def default_allocator() -> HostAllocator:
    global _default
    with _default_lock:
        if _default is None:
            _default = HostAllocator()
        return _default


def memory_stats() -> Dict[str, int]:
    """paddle.device.*.memory_stats equivalent for host staging memory."""
    return default_allocator().stats()


def max_memory_allocated() -> int:
    return default_allocator().stats()["peak_allocated"]


def max_memory_reserved() -> int:
    return default_allocator().stats()["peak_reserved"]

from . import dtype, errors, flags, generator
from .tensor import Tensor, Parameter

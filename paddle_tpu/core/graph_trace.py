"""Shared layer-graph tracer + jaxpr walking utilities.

One tracing forward that records, at TOP level (outside any leaf
layer), both leaf-layer calls and functional registry ops — the
machinery behind `onnx/export.py` (graph emission) and
`inference/passes.py` (dataflow-verified folds). Keeping it in one
place means tuple outputs, kwargs tensors and consumer accounting
behave identically for every consumer of the trace.

The jaxpr side (``iter_jaxpr_eqns`` / ``sub_jaxprs``) is the shared
walk every jaxpr-level analysis uses (``paddle_tpu/analysis``): one
recursive traversal that sees through scan/while/cond/pjit/remat/
shard_map bodies, yielding each equation with the control-flow path
that reaches it — so a pass written against flat equations works
unchanged on the serving graphs, whose hot loops all live inside
``lax.scan``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Set, Tuple

import jax
from jax._src import core as jax_core

from .tensor import Tensor


# ---------------------------------------------------------------------------
# jaxpr traversal
# ---------------------------------------------------------------------------

def sub_jaxprs(eqn) -> List[Tuple[str, "jax_core.Jaxpr"]]:
    """The (label, jaxpr) bodies nested inside one equation.

    Covers every closed-jaxpr-carrying param jax uses across versions
    (scan/while/cond/pjit/custom_vjp/remat/shard_map/...) by TYPE, not
    by a primitive-name allowlist — a new primitive with a jaxpr param
    is walked automatically instead of silently skipped."""
    out = []
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for i, v in enumerate(vals):
            label = name if len(vals) == 1 else f"{name}[{i}]"
            if isinstance(v, jax_core.ClosedJaxpr):
                out.append((label, v.jaxpr))
            elif isinstance(v, jax_core.Jaxpr):
                out.append((label, v))
    return out


def iter_jaxpr_eqns(jaxpr, path: Tuple = ()) -> Iterator[Tuple[Tuple,
                                                               Any]]:
    """Yield ``(path, eqn)`` for every equation, depth-first, where
    ``path`` is the chain of ``(primitive_name, param_label)`` frames
    that reaches the equation (empty for top level). ``jaxpr`` may be a
    ``ClosedJaxpr`` or a raw ``Jaxpr``."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield path, eqn
        for label, sub in sub_jaxprs(eqn):
            yield from iter_jaxpr_eqns(
                sub, path + ((eqn.primitive.name, label),))


# ---------------------------------------------------------------------------
# jaxpr rewriting support (analysis/rewrite.py builds on these)
# ---------------------------------------------------------------------------

def producer_map(jaxpr) -> Dict[Any, Tuple[int, Any]]:
    """var -> (eqn_index, eqn) for every var DEFINED at this level of
    ``jaxpr`` (sub-jaxpr internals excluded: a pattern is a same-level
    dataflow chain; values crossing a control-flow boundary are inputs,
    not intermediates)."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    out: Dict[Any, Tuple[int, Any]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for o in eqn.outvars:
            out[o] = (i, eqn)
    return out


def var_use_sites(jaxpr) -> Dict[Any, List[int]]:
    """var -> list of eqn indices consuming it at this level; an
    appearance in ``jaxpr.outvars`` adds the sentinel ``-1``. The
    exclusivity test rewrites need: a matched intermediate whose uses
    are not all inside the match cannot be deleted with it."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    uses: Dict[Any, List[int]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for a in eqn.invars:
            if not isinstance(a, jax_core.Literal):
                uses.setdefault(a, []).append(i)
    for o in jaxpr.outvars:
        if not isinstance(o, jax_core.Literal):
            uses.setdefault(o, []).append(-1)
    return uses


def eval_eqn(eqn, invals: List[Any]):
    """Re-issue one equation on concrete/traced values exactly as
    ``jax.core.eval_jaxpr`` would (same primitive, same params).
    Returns the flat list of outputs."""
    subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
    ans = eqn.primitive.bind(*subfuns, *invals, **bind_params)
    return list(ans) if eqn.primitive.multiple_results else [ans]


def bind_rewritten(eqn, run_body, invals: List[Any]) -> List[Any]:
    """Re-issue a jaxpr-carrying equation with every body evaluated by
    ``run_body(closed_jaxpr, *flat_args) -> flat_outs`` — the hook a
    rewriter uses to splice replacements into scan/while/cond/pjit
    bodies while the surrounding control flow is rebuilt 1:1 (same trip
    counts, same carry structure, so numerics outside the rewritten
    subgraphs are untouched). Raises ``NotImplementedError`` for
    jaxpr-carrying primitives without a rebuild recipe (custom_vjp
    bodies, shard_map, ...): the caller falls back to binding the eqn
    unchanged, i.e. those bodies are opaque to rewriting."""
    import jax
    from jax import lax
    prim = eqn.primitive.name
    p = eqn.params
    if prim == "scan":
        nc, ncar = p["num_consts"], p["num_carry"]
        body = p["jaxpr"]
        consts = tuple(invals[:nc])
        carry = tuple(invals[nc:nc + ncar])
        xs = tuple(invals[nc + ncar:])

        def f(c, x):
            outs = run_body(body, *consts, *c, *(x or ()))
            return tuple(outs[:ncar]), tuple(outs[ncar:])

        carry_out, ys = lax.scan(
            f, carry, xs if xs else None, length=p["length"],
            reverse=p["reverse"], unroll=p.get("unroll", 1))
        return list(carry_out) + list(ys)
    if prim in ("pjit", "closed_call", "core_call"):
        # inline: the rewritten whole-program is re-jitted by its
        # caller anyway, so the inner jit boundary carries no value
        return list(run_body(p["jaxpr"], *invals))
    if prim == "cond":
        branches = p["branches"]
        idx, *ops = invals
        fns = [(lambda b: lambda *a: tuple(run_body(b, *a)))(b)
               for b in branches]
        out = lax.switch(idx, fns, *ops)
        return list(out)
    if prim == "while":
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cconsts = tuple(invals[:cn])
        bconsts = tuple(invals[cn:cn + bn])
        init = tuple(invals[cn + bn:])
        out = lax.while_loop(
            lambda c: run_body(p["cond_jaxpr"], *cconsts, *c)[0],
            lambda c: tuple(run_body(p["body_jaxpr"], *bconsts, *c)),
            init)
        return list(out)
    if prim in ("remat2", "checkpoint"):
        body = p["jaxpr"]
        closed = (body if isinstance(body, jax_core.ClosedJaxpr)
                  else jax_core.ClosedJaxpr(body, ()))
        fn = jax.checkpoint(lambda *a: tuple(run_body(closed, *a)),
                            policy=p.get("policy"),
                            prevent_cse=p.get("prevent_cse", True))
        return list(fn(*invals))
    raise NotImplementedError(
        f"no rebuild recipe for jaxpr-carrying primitive {prim!r}")


@dataclass
class TraceResult:
    #: ordered top-level events:
    #:   ("layer", layer, inputs, output) | ("op", name, args, kwargs, out)
    events: List[Tuple] = field(default_factory=list)
    #: id(tensor) -> number of top-level consumptions (leaf-layer inputs
    #: + depth-0 op args + model outputs)
    consumers: Dict[int, int] = field(default_factory=dict)
    #: ids of every tensor PRODUCED during the trace
    traced_ids: Set[int] = field(default_factory=set)
    #: per-layer top-level call counts (object identity)
    layer_calls: Dict[int, int] = field(default_factory=dict)
    #: the model's return value
    y: Any = None
    #: strong refs — a GC'd tensor's id would be recycled mid-trace
    keep: List[Any] = field(default_factory=list)

    def consumed(self, v):
        if isinstance(v, Tensor):
            self.keep.append(v)
            self.consumers[id(v)] = self.consumers.get(id(v), 0) + 1

    def produced(self, out):
        for t in (out if isinstance(out, (tuple, list)) else (out,)):
            if isinstance(t, Tensor):
                self.keep.append(t)
                self.traced_ids.add(id(t))


def trace_layer_graph(model, x: Tensor, leaves=None) -> TraceResult:
    """Run ``model(x)`` in eval/no-grad with recording hooks installed;
    restores training mode and hooks afterwards.

    ``leaves`` sets the trace granularity: the layers treated as
    ATOMIC (one "layer" event each; anything inside them — sublayer
    calls, functional ops — is masked by the depth counter). Default
    None = the model's leaf sublayers (the ONNX-export shape). The
    auto-parallel Engine's pp forward-order check passes its top-level
    UNITS here, so "op" events then mean exactly "functional math
    between units" — glue a stage loop cannot reproduce."""
    from ..autograd import tape as _tape
    from ..ops import registry as _registry

    res = TraceResult()
    depth = [0]
    hooks = []

    def pre(l, inputs):
        if depth[0] == 0:
            for v in (inputs if isinstance(inputs, tuple) else (inputs,)):
                res.consumed(v)
        depth[0] += 1

    def post(l, inputs, output):
        depth[0] -= 1
        res.produced(output)
        if depth[0] == 0:
            res.events.append(("layer", l, inputs, output))
            res.layer_calls[id(l)] = res.layer_calls.get(id(l), 0) + 1
            src = inputs[0] if isinstance(inputs, tuple) else inputs
            res.keep.append(src)

    if leaves is None:
        leaves = [s for _, s in model.named_sublayers(include_self=True)
                  if not list(s.sublayers())]
    else:
        leaves = list(leaves)
    for s in leaves:
        hooks.append(s.register_forward_pre_hook(pre))
        hooks.append(s.register_forward_post_hook(post))

    # pre-hooks receive only POSITIONAL inputs (Layer.__call__, paddle
    # hook parity) — wrap each leaf's forward so tensors passed as
    # kwargs count as consumers too (depth == 1 inside a top-level
    # call: the pre-hook already incremented)
    wrapped_leaves = []

    def _wrap_forward(orig):
        def wrapped(*a, **kw):
            if depth[0] == 1 and kw:
                for v in kw.values():
                    jax.tree_util.tree_map(
                        res.consumed, v,
                        is_leaf=lambda t: isinstance(t, Tensor))
            return orig(*a, **kw)
        return wrapped

    for s in leaves:
        wrapped_leaves.append((s, s.__dict__.get("forward")))
        object.__setattr__(s, "forward", _wrap_forward(s.forward))

    def op_rec(name, args, kwargs, out):
        res.produced(out)
        if depth[0] == 0:
            for a in list(args) + list(kwargs.values()):
                jax.tree_util.tree_map(
                    res.consumed, a,
                    is_leaf=lambda v: isinstance(v, Tensor))
            res.events.append(("op", name, args, kwargs, out))

    was_training = model.training
    model.eval()
    prev_hook = _registry._ONNX_TRACE
    _registry._ONNX_TRACE = op_rec
    try:
        with _tape.no_grad():
            res.y = model(x)
    finally:
        _registry._ONNX_TRACE = prev_hook
        if was_training:
            model.train()
        for h in hooks:
            h.remove()
        for s, saved in wrapped_leaves:
            if saved is None:
                s.__dict__.pop("forward", None)
            else:
                object.__setattr__(s, "forward", saved)
    # the model's outputs are consumers too: a tensor that is RETURNED
    # must not be treated as exclusively feeding its one layer consumer
    # (walk the FULL structure — dicts/nested containers included)
    jax.tree_util.tree_map(res.consumed, res.y,
                           is_leaf=lambda t: isinstance(t, Tensor))
    return res

"""Seeded RNG state.

TPU-native analogue of `phi::Generator` (paddle/phi/core/generator.h): the
reference keeps a per-device Philox state; JAX's threefry keys are already
counter-based, so the global generator holds one key and splits it per draw.
Inside jit-captured code, ops take explicit keys instead (functional style);
this stateful generator serves the eager API surface.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            # lazy: PRNGKey materialises a device array, which would
            # initialise the JAX backend at import time (the default
            # generator is created when paddle_tpu is imported)
            self._key = None
        return self

    def seed(self):
        return self._seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def ensure_key(self):
        """The current key array (materialising it lazily)."""
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            return self._key

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            return np.asarray(self._key)

    def set_state(self, state):
        import jax.numpy as jnp
        self._key = jnp.asarray(state, dtype=jnp.uint32)


_default_generator = Generator(np.random.randint(0, 2**31 - 1))


def default_generator() -> Generator:
    return _default_generator


def seed(value: int) -> Generator:
    """Set the global RNG seed (paddle.seed)."""
    return _default_generator.manual_seed(value)


def next_key():
    return _default_generator.next_key()

"""Typed error hierarchy + enforce helpers.

TPU-native analogue of the reference's PADDLE_ENFORCE machinery
(paddle/phi/core/enforce.h, paddle/common/errors.h): typed exceptions plus
``enforce_*`` check helpers that raise with useful context.
"""
from __future__ import annotations


class FrameworkError(Exception):
    pass


class InvalidArgumentError(FrameworkError, ValueError):
    pass


class NotFoundError(FrameworkError, KeyError):
    pass


class OutOfRangeError(FrameworkError, IndexError):
    pass


class AlreadyExistsError(FrameworkError):
    pass


class PermissionDeniedError(FrameworkError):
    pass


class UnimplementedError(FrameworkError, NotImplementedError):
    pass


class UnavailableError(FrameworkError, RuntimeError):
    pass


class FatalError(FrameworkError, RuntimeError):
    pass


class PreconditionNotMetError(FrameworkError, RuntimeError):
    pass


def enforce(cond, msg: str, exc=InvalidArgumentError):
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg: str = "", exc=InvalidArgumentError):
    if a != b:
        raise exc(f"expected {a!r} == {b!r}. {msg}")


def enforce_shape_rank(shape, rank: int, name: str = "input"):
    if len(shape) != rank:
        raise InvalidArgumentError(
            f"{name} expected rank {rank}, got rank {len(shape)} (shape {list(shape)})")

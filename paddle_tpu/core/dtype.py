"""Data types for the TPU-native framework.

Mirrors the reference's dtype surface (paddle/phi/common/data_type.h and
python `paddle.float32`-style module attributes) on top of numpy/jax dtypes.
TPU-first: bfloat16 is a first-class dtype; float64 is supported but
discouraged (XLA emulates it slowly on TPU).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes


class DType:
    """A framework dtype. Wraps a numpy dtype; compares equal to strings,
    numpy dtypes, and other DType instances."""

    __slots__ = ("name", "np_dtype")

    _registry: dict = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        DType._registry[name] = self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or _ALIASES.get(other) == self.name
        try:
            return self.np_dtype == np.dtype(other)
        except TypeError:
            return NotImplemented

    def __ne__(self, other):
        r = self.__eq__(other)
        return NotImplemented if r is NotImplemented else not r

    def __hash__(self):
        return hash(self.name)

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize

    def is_floating(self) -> bool:
        return self.name in ("float16", "bfloat16", "float32", "float64",
                             "float8_e4m3fn", "float8_e5m2")

    def is_complex(self) -> bool:
        return self.name in ("complex64", "complex128")

    def is_integer(self) -> bool:
        return self.name in ("int8", "int16", "int32", "int64", "uint8",
                             "uint16", "uint32", "uint64")


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
float8_e4m3fn = DType("float8_e4m3fn", ml_dtypes.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", ml_dtypes.float8_e5m2)
uint16 = DType("uint16", np.uint16)
uint32 = DType("uint32", np.uint32)
uint64 = DType("uint64", np.uint64)

_ALIASES = {"float": "float32", "double": "float64", "half": "float16",
            "int": "int32", "long": "int64", "bool_": "bool"}

_BY_NP = {d.np_dtype: d for d in DType._registry.values()}
# np.bool_ and bool both map
_BY_NP[np.dtype(bool)] = bool_


def to_framework_dtype(d) -> DType:
    """Convert any dtype-like (str, np.dtype, jnp dtype, DType) to DType."""
    if d is None:
        return None
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        name = _ALIASES.get(d, d)
        try:
            return DType._registry[name]
        except KeyError:
            raise ValueError(f"unknown dtype: {d!r}") from None
    npd = np.dtype(d)
    try:
        return _BY_NP[npd]
    except KeyError:
        raise ValueError(f"unsupported dtype: {d!r}") from None


def to_jax_dtype(d):
    """Convert dtype-like to a numpy dtype usable by jax.numpy.

    TPU-first canonicalization: 64-bit ints/floats are stored as 32-bit
    (JAX's default x64-disabled world; the TPU has no fast int64/float64
    path). The API accepts 'int64'/'float64' everywhere for reference parity
    but computes in 32-bit, like jax itself.
    """
    if d is None:
        return None
    npd = to_framework_dtype(d).np_dtype
    import jax
    if not jax.config.jax_enable_x64:
        npd = _X64_NARROW.get(npd, npd)
    return npd


_X64_NARROW = {
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


_default_dtype = float32


def set_default_dtype(d):
    global _default_dtype
    d = to_framework_dtype(d)
    if not d.is_floating():
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype.name


def default_float_dtype() -> DType:
    return _default_dtype


def promote_types(a: DType, b: DType) -> DType:
    return to_framework_dtype(jnp.promote_types(a.np_dtype, b.np_dtype))


def iinfo(d):
    return np.iinfo(to_jax_dtype(d))


def finfo(d):
    return ml_dtypes.finfo(to_jax_dtype(d))

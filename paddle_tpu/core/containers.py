"""TensorArray and SelectedRows — the reference's auxiliary tensor
container types (paddle/fluid/framework/lod_tensor_array.h,
paddle/phi/core/selected_rows.h + python paddle.tensor.array_* ops).

TPU-native notes:
  * TensorArray backs dynamic write/read sequences. Under jit, prefer
    lax.scan; eagerly (and for API parity) this is a growable list with
    write/read/stack/concat and the array_* functional ops.
  * SelectedRows is the reference's sparse-gradient carrier (embedding
    grads as {rows, values}). On TPU, gradients stay dense — XLA fuses
    the scatter-add — so SelectedRows here is a faithful data type with
    to_dense()/from_dense() for interop, not a dispatch path.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from .tensor import Tensor


class TensorArray:
    """Growable array of Tensors (reference: LoDTensorArray)."""

    def __init__(self, tensors: Optional[Sequence[Tensor]] = None):
        self._items: List[Tensor] = list(tensors or [])

    def append(self, t) -> "TensorArray":
        self._items.append(t if isinstance(t, Tensor) else Tensor(t))
        return self

    def write(self, index: int, t) -> "TensorArray":
        t = t if isinstance(t, Tensor) else Tensor(t)
        if index == len(self._items):
            self._items.append(t)
        elif 0 <= index < len(self._items):
            self._items[index] = t
        else:
            raise IndexError(
                f"write at {index} outside [0, {len(self._items)}]")
        return self

    def read(self, index: int) -> Tensor:
        return self._items[index]

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def stack(self, axis: int = 0) -> Tensor:
        from .. import ops
        return ops.stack(list(self._items), axis=axis)

    def concat(self, axis: int = 0) -> Tensor:
        from .. import ops
        return ops.concat(list(self._items), axis=axis)


def create_array(dtype=None, initialized_list=None) -> TensorArray:
    """paddle.tensor.create_array."""
    return TensorArray(initialized_list)


def array_write(x, i, array: Optional[TensorArray] = None) -> TensorArray:
    """paddle.tensor.array_write (i may be a 0-d Tensor)."""
    if array is None:
        array = TensorArray()
    array.write(int(i.numpy()) if isinstance(i, Tensor) else int(i), x)
    return array


def array_read(array: TensorArray, i) -> Tensor:
    return array.read(int(i.numpy()) if isinstance(i, Tensor) else int(i))


def array_length(array: TensorArray) -> Tensor:
    return Tensor(jnp.asarray(len(array), jnp.int64), stop_gradient=True)


class SelectedRows:
    """{height, rows, values} sparse row container
    (phi/core/selected_rows.h)."""

    def __init__(self, rows, values, height: int):
        self.rows = (rows if isinstance(rows, Tensor)
                     else Tensor(jnp.asarray(rows, jnp.int32),
                                 stop_gradient=True))
        self.value = values if isinstance(values, Tensor) else Tensor(values)
        self.height = int(height)
        if self.rows.shape[0] != self.value.shape[0]:
            raise ValueError(
                f"rows ({self.rows.shape[0]}) and values "
                f"({self.value.shape[0]}) must pair up")

    def to_dense(self) -> Tensor:
        dense = jnp.zeros((self.height,) + tuple(self.value.shape[1:]),
                          self.value.data.dtype)
        return Tensor(dense.at[self.rows.data].add(self.value.data))

    @staticmethod
    def from_dense(dense, rows=None) -> "SelectedRows":
        d = dense.data if isinstance(dense, Tensor) else jnp.asarray(dense)
        if rows is None:
            nz = np.nonzero(np.any(
                np.asarray(d).reshape(d.shape[0], -1) != 0, axis=1))[0]
            rows = jnp.asarray(nz, jnp.int32)
        else:
            rows = jnp.asarray(rows, jnp.int32)
        return SelectedRows(rows, Tensor(d[rows]), d.shape[0])

"""Build + load the native C++ runtime library.

The reference's runtime core is native C++ (SURVEY.md §2.1: allocator
facade, TCPStore, shm transfer). Ours is too: paddle_tpu/csrc/*.cc compiles
into one libpaddle_tpu_rt.so at first use (g++ -O2 -shared; no network, no
extra deps) and binds via ctypes. Everything degrades gracefully: if no
toolchain is available, ``lib()`` returns None and pure-Python fallbacks
take over (callers must check).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_SO = os.path.join(_CSRC, "libpaddle_tpu_rt.so")
_SOURCES = ["allocator.cc", "shm_ring.cc", "tcp_store.cc"]


def _build() -> Optional[str]:
    srcs = [os.path.join(_CSRC, s) for s in _SOURCES]
    if os.path.exists(_SO) and all(
            os.path.getmtime(_SO) >= os.path.getmtime(s) for s in srcs):
        return _SO
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           *srcs, "-lrt", "-o", _SO + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=240)
        os.replace(_SO + ".tmp", _SO)
        return _SO
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError, OSError) as e:
        err = getattr(e, "stderr", b"")
        if os.environ.get("PADDLE_TPU_NATIVE_REQUIRED"):
            raise RuntimeError(
                f"native runtime build failed: {err!r}") from e
        return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, i64, vp, cp = (ctypes.c_uint64, ctypes.c_int64, ctypes.c_void_p,
                        ctypes.c_char_p)
    sigs = {
        "pt_alloc_create": ([u64], vp),
        "pt_alloc_destroy": ([vp], None),
        "pt_alloc_malloc": ([vp, u64], vp),
        "pt_alloc_free": ([vp, vp], ctypes.c_int),
        "pt_alloc_stats": ([vp, ctypes.POINTER(u64)], None),
        "pt_alloc_reset_peak": ([vp], None),
        "pt_ring_create": ([cp, u64], vp),
        "pt_ring_attach": ([cp], vp),
        "pt_ring_push": ([vp, vp, u64, i64], ctypes.c_int),
        "pt_ring_next_size": ([vp], i64),
        "pt_ring_pop": ([vp, vp, u64, i64], i64),
        "pt_ring_close": ([vp], None),
        "pt_ring_capacity": ([vp], u64),
        "pt_ring_wait_space": ([vp, u64, i64], ctypes.c_int),
        "pt_ring_destroy": ([vp], None),
        "pt_store_server_start": ([ctypes.c_int], vp),
        "pt_store_server_stop": ([vp], None),
        "pt_store_connect": ([cp, ctypes.c_int, ctypes.c_int], vp),
        "pt_store_disconnect": ([vp], None),
        "pt_store_set": ([vp, cp, vp, ctypes.c_uint32], ctypes.c_int),
        "pt_store_get": ([vp, cp, vp, ctypes.c_uint32], i64),
        "pt_store_add": ([vp, cp, i64], i64),
        "pt_store_wait": ([vp, cp], ctypes.c_int),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The native runtime library, building it on first call (None if no
    toolchain and PADDLE_TPU_NATIVE_REQUIRED is unset)."""
    global _LIB, _TRIED
    with _LIB_LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        so = _build()
        if so is not None:
            _LIB = _bind(ctypes.CDLL(so))
        return _LIB


def available() -> bool:
    return lib() is not None

"""The Tensor facade over jax.Array.

TPU-native redesign of the reference's `paddle::Tensor`
(paddle/phi/api/include/tensor.h:82) + `AutogradMeta`
(paddle/fluid/eager/autograd_meta.h): one Python object wrapping an immutable
`jax.Array` plus autograd metadata (tape node link, ``.grad``, hooks,
``stop_gradient``). All math lives in pure-JAX op functions (paddle_tpu.ops);
in-place APIs rebind ``_data`` functionally.

Tensor is a registered JAX pytree, so user functions over Tensors can be
passed straight to jax.jit / shard_map; the autograd metadata is dropped at
the trace boundary (matching the reference, where DenseTensor crossing into a
static program loses its eager grad node).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from ..autograd import tape as _tape


class Tensor:
    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "_out_index",
                 "_grad_hooks", "_retain_grads", "name", "persistable",
                 "_partial_dims", "_partial_reduce",  # dist Partial state
                 "__weakref__")

    def __init__(self, data, stop_gradient: bool = True, name: str = ""):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)) and \
                not getattr(data, "_is_lazy", False):
            # _is_lazy: jit/segments.LazyValue payloads pass through
            # unconverted (conversion would force the pending segment)
            data = _np_to_jax(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node = None
        self._out_index = 0
        self._grad_hooks = None
        self._retain_grads = False
        self.name = name
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        return self._data

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else value

    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    # paddle: Tensor.size is numel (an int), not a method
    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self) -> dtypes.DType:
        return dtypes.to_framework_dtype(self._data.dtype)

    @property
    def place(self):
        try:
            devs = getattr(self._data, "devices", None)
            if devs is None:
                return "traced"
            return str(next(iter(self._data.devices())))
        except Exception:
            return "traced"

    @property
    def T(self) -> "Tensor":
        from .. import ops
        return ops.transpose(self, list(range(self.ndim))[::-1])

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def astype(self, dt) -> "Tensor":
        from .. import ops
        return ops.cast(self, dt)

    cast = astype

    def to(self, *args, **kwargs) -> "Tensor":
        """to(dtype) / to(device) / to(device, dtype). Device moves use
        jax.device_put; 'cpu'/'tpu' strings accepted."""
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, dtypes.DType)) and _is_dtype_like(a):
                out = out.astype(a)
            elif isinstance(a, str):
                dev = _resolve_device(a)
                out = Tensor(jax.device_put(out._data, dev),
                             stop_gradient=out.stop_gradient)
        return out

    def cpu(self):
        return self.to("cpu")

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return self.dtype.itemsize

    def dim(self) -> int:
        return self.ndim

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _tape.backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._data))
        else:
            self._grad = None

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook: Callable):
        if self._grad_hooks is None:
            self._grad_hooks = []
        self._grad_hooks.append(hook)

        class _Handle:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Handle(self._grad_hooks, hook)

    def _apply_grad_hooks(self, g_arr):
        if not self._grad_hooks:
            return g_arr
        # under create_graph the cotangent is already a (taped) Tensor —
        # keep it one so hooks stay differentiable
        was_tensor = isinstance(g_arr, Tensor)
        g = g_arr if was_tensor else Tensor(g_arr, stop_gradient=True)
        for hook in self._grad_hooks:
            out = hook(g)
            if out is not None:
                g = out if isinstance(out, Tensor) else Tensor(out)
        return g if was_tensor else g._data

    # -- in-place-style APIs (functional rebind) ----------------------------
    def set_value(self, value):
        arr = value._data if isinstance(value, Tensor) else _np_to_jax(value)
        self._data = arr.astype(self._data.dtype) if arr.dtype != self._data.dtype else arr

    def copy_(self, other):
        self.set_value(other)
        return self

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def scale_(self, scale):
        self._data = self._data * scale
        return self

    def add_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data + o
        return self

    def subtract_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data - o
        return self

    def multiply_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data * o
        return self

    def clip_(self, min=None, max=None):
        self._data = jnp.clip(self._data, min, max)
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        from ..ops import registry
        idx = _unwrap_index(idx)
        # key passed as a (static) kwarg, not a closure cell: trace
        # consumers (onnx export) need to SEE the index
        return registry.call_op("getitem", lambda x, key: x[key], (self,),
                                {"key": idx})

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        v = value._data if isinstance(value, Tensor) else value
        if getattr(self._data, "_is_lazy", False):
            # pending segment output (jit/segments): in-place update
            # needs the concrete array — force the segment
            self._data = self._data._force()
        if getattr(v, "_is_lazy", False):
            v = v._force()
        self._data = self._data.at[idx].set(v)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- python protocol ----------------------------------------------------
    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {np.asarray(self._data)!r})")

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a Tensor with more than one element is "
                "ambiguous. Use .any() or .all()")
        return bool(self._data)

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __index__(self):
        return int(self._data)

    def __hash__(self):
        return id(self)

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return repr(self)

    # Arithmetic dunders are installed by paddle_tpu.ops at import time.


class Parameter(Tensor):
    """A trainable Tensor (reference: paddle Parameter / EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed")

    def __init__(self, data, trainable: bool = True, name: str = ""):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.persistable = True

    @property
    def requires_grad(self):
        return not self.stop_gradient

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# -- helpers ---------------------------------------------------------------

def _np_to_jax(data):
    """Convert python/numpy data to a jax array with paddle-style defaults:
    python floats -> default float dtype (float32), ints -> int64."""
    if isinstance(data, (bool, int, float, complex)) or (
            isinstance(data, (list, tuple)) or isinstance(data, np.ndarray)):
        arr = np.asarray(data)
        if arr.dtype == np.float64 and not isinstance(data, np.ndarray):
            arr = arr.astype(dtypes.default_float_dtype().np_dtype)
        return jnp.asarray(arr)
    return jnp.asarray(data)


def _is_dtype_like(a) -> bool:
    if isinstance(a, dtypes.DType):
        return True
    try:
        dtypes.to_framework_dtype(a)
        return True
    except (ValueError, TypeError):
        return False


def _resolve_device(name: str):
    name = name.lower().split(":")[0]
    for d in jax.devices():
        if d.platform in (name, {"gpu": "cuda"}.get(name, name)):
            return d
    for d in jax.local_devices(backend="cpu"):
        return d
    raise ValueError(f"no device matching {name!r}")


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in idx)
    return idx


# -- pytree registration ---------------------------------------------------

def _tensor_flatten(t: Tensor):
    return (t._data,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    t = Tensor.__new__(Tensor)
    t._data = children[0]
    t.stop_gradient, t.name = aux
    t._grad = None
    t._node = None
    t._out_index = 0
    t._grad_hooks = None
    t._retain_grads = False
    t.persistable = False
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


def _param_flatten(p: Parameter):
    return (p._data,), (p.stop_gradient, p.name)


def _param_unflatten(aux, children):
    p = Parameter.__new__(Parameter)
    p._data = children[0]
    p.stop_gradient, p.name = aux
    p._grad = None
    p._node = None
    p._out_index = 0
    p._grad_hooks = None
    p._retain_grads = False
    p.persistable = True
    p.trainable = not p.stop_gradient
    p.optimize_attr = {"learning_rate": 1.0}
    p.regularizer = None
    p.is_distributed = False
    return p


jax.tree_util.register_pytree_node(Parameter, _param_flatten, _param_unflatten)

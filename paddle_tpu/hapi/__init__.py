"""paddle_tpu.hapi — high-level Model.fit API (reference: python/paddle/hapi/)."""
from .model import Model  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
    LRScheduler,
)
from .summary import summary  # noqa: F401

"""High-level Model API: prepare / fit / evaluate / predict.

Reference: python/paddle/hapi/model.py:1082 (Model.fit), :1808 (predict) —
the Keras-style trainer over a Layer, with metrics and callbacks.

TPU note: the train loop is eager op-by-op (tape autograd) like the
reference's dygraph path; each batch is device_put once and all math stays
on device. For the jit-compiled whole-step path use models/llama-style
functional train steps or jit.to_static on the Layer.
"""
from __future__ import annotations

import os
from typing import List

import numpy as np

from ..io.dataloader import DataLoader
from ..io.dataset import Dataset
from ..metric import Metric
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle_tpu.metric.Metric")
        return self

    def parameters(self):
        return self.network.parameters()

    # -- per-batch ----------------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        lbls = _to_list(labels)
        loss = self._loss(*(outs + lbls))
        if isinstance(loss, (list, tuple)):
            loss = sum(loss)
        if loss.ndim > 0:
            loss = loss.mean()
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        outputs = self.network(*_to_list(inputs))
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return [float(loss.numpy())], metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        outputs = self.network(*_to_list(inputs))
        loss = self._compute_loss(outputs, labels)
        metrics = self._update_metrics(outputs, labels)
        return [float(loss.numpy())], metrics

    def predict_batch(self, inputs):
        self.network.eval()
        out = self.network(*_to_list(inputs))
        return [o.numpy() if hasattr(o, "numpy") else np.asarray(o)
                for o in _to_list(out)]

    def _update_metrics(self, outputs, labels):
        res = []
        out = _to_list(outputs)[0]
        lbl = _to_list(labels)[0] if labels is not None else None
        for m in self._metrics:
            m.update(*_to_list(m.compute(out, lbl)))
            res.append(m.accumulate())
        return res

    # -- loops --------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=True)
        return data  # assume iterable of batches

    def _metric_logs(self, prefix=""):
        logs = {}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            for n, v in zip(names, vals):
                logs[prefix + n] = v
        return logs

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert self._optimizer is not None and self._loss is not None, \
            "call prepare(optimizer, loss) before fit"
        loader = self._loader(train_data, batch_size, shuffle, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir, metrics=self._metrics)

        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, lbls = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                losses, _ = self.train_batch(ins, lbls, update=update)
                logs = {"loss": losses[0], **self._metric_logs()}
                cbks.on_train_batch_end(step, logs)
                it += 1
                if (num_iters and it >= num_iters) or self.stop_training:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose, callbacks=callbacks,
                              num_workers=num_workers)
            if (num_iters and it >= num_iters) or self.stop_training:
                break
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size,
            steps=len(loader) if hasattr(loader, "__len__") else None,
            log_freq=log_freq, verbose=verbose, metrics=self._metrics,
            mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbls = self._split_batch(batch)
            l, _ = self.eval_batch(ins, lbls)
            losses.append(l[0])
            cbks.on_eval_batch_end(step, {"loss": l[0]})
        logs = {"loss": float(np.mean(losses)) if losses else 0.0,
                **self._metric_logs()}
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n)]
        return outputs

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                # (x..., y) pairs: predict drops the trailing label a
                # labelled Dataset yields (reference predict does the same
                # via its _inputs spec)
                return batch[:-1], (batch[-1:] if has_labels else None)
            return batch, None
        return [batch], None

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework import io as fio
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import io as fio
        self.network.set_state_dict(fio.load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fio.load(path + ".pdopt"))
        return self

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)

"""hapi callbacks (reference: python/paddle/hapi/callbacks.py —
Callback protocol + ProgBarLogger/ModelCheckpoint/EarlyStopping/LRScheduler,
driven by Model.fit)."""
from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kw):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kw)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Prints per-epoch progress lines (reference ProgBarLogger, minus the
    terminal progress bar — logs go to stdout for CI friendliness)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def _fmt(self, logs):
        out = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                out.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple, np.ndarray)):
                out.append(f"{k}: {np.asarray(v).ravel()}")
        return " - ".join(out)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            print(f"step {step}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"Epoch {epoch}: {self._fmt(logs)} ({dt:.1f}s)")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval: {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = (self.baseline if self.baseline is not None
                     else (-np.inf if self.mode == "max" else np.inf))

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).ravel()[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each epoch/batch (reference
    LRScheduler callback)."""

    def __init__(self, by_step: bool = False, by_epoch: bool = True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None)

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [],
    })
    return cl

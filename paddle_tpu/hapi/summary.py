"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations


import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer parameter table; returns
    {'total_params': N, 'trainable_params': N}."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape))
        total += n
        if not getattr(p, "stop_gradient", False):
            trainable += n
        rows.append((name, tuple(p.shape), n))
    w = max([len(r[0]) for r in rows], default=10) + 2
    print(f"{'Layer (param)':<{w}}{'Shape':<20}{'Param #':>12}")
    print("-" * (w + 32))
    for name, shape, n in rows:
        print(f"{name:<{w}}{str(shape):<20}{n:>12,}")
    print("-" * (w + 32))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}

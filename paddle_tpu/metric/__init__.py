"""paddle_tpu.metric — training metrics.

Reference: python/paddle/metric/metrics.py (Metric base + Accuracy/
Precision/Recall/Auc with update/accumulate/reset/name protocol, consumed
by hapi Model.fit).
"""
from __future__ import annotations

import abc
from typing import List, Sequence, Union

import numpy as np


def _to_np(x) -> np.ndarray:
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Optional fast-path computed inside the traced step; default
        passes predictions/labels through to update()."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label):
        pred = _to_np(pred)
        label = _to_np(label)
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]
        # [N, maxk] correctness matrix
        topk_idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        return (topk_idx == label[..., None]).astype(np.float32)

    def update(self, correct):
        correct = _to_np(correct)
        num = correct.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(axis=-1).sum()
        self.count += num
        res = self.total[0] / max(self.count, 1)
        return res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision over 0/1 predictions (metrics.py Precision)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_to_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_to_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _to_np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold bucketing (metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _to_np(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        # sweep thresholds high->low accumulating trapezoids
        for i in range(self.num_thresholds, -1, -1):
            p, n = self._stat_pos[i], self._stat_neg[i]
            auc += n * (tot_pos + p / 2.0)
            tot_pos += p
            tot_neg += n
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional top-k accuracy."""
    m = Accuracy(topk=(k,))
    return float(m.update(m.compute(input, label)))

"""Eager autograd engine: a real tape over per-op JAX VJPs.

TPU-native redesign of the reference's eager autograd
(paddle/fluid/eager/grad_node_info.h:197 `GradNodeBase`,
paddle/fluid/eager/backward.cc:105,439 `RunBackward`): instead of generated
C++ grad nodes per op, every differentiable eager op records one `GradNode`
holding the `jax.vjp` pullback of its pure-JAX forward function. Backward is
a reverse-topological sweep (nodes carry a monotonic sequence id, so sorting
by id descending is a valid topological order of the DAG).

This gives full eager semantics the functional substrate lacks on its own:
``stop_gradient``, ``retain_graph``, gradient accumulation into ``.grad``,
tensor hooks, and ``PyLayer`` — while the math inside every node is still
pure JAX, so the same ops trace cleanly under ``jax.jit``/``jax.grad``.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp


class _TapeState(threading.local):
    def __init__(self):
        self.grad_enabled = True


_state = _TapeState()
_seq = itertools.count()


def grad_enabled() -> bool:
    return _state.grad_enabled


def set_grad_enabled(mode: bool) -> bool:
    prev = _state.grad_enabled
    _state.grad_enabled = bool(mode)
    return prev


class no_grad:
    """Context manager / decorator disabling gradient recording."""

    def __enter__(self):
        self._prev = set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class GradNode:
    """One recorded op on the tape.

    vjp_fn: pullback taking the output-cotangent pytree and returning a tuple
    of cotangents, one per differentiable input tensor.
    """

    __slots__ = ("op_name", "vjp_fn", "inputs", "out_avals", "out_treedef",
                 "id", "pure_fn", "__weakref__")

    def __init__(self, op_name: str, vjp_fn: Callable, inputs: Sequence,
                 out_avals: List, out_treedef, pure_fn: Callable = None):
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # Tensors (strong refs keep graph alive)
        self.out_avals = out_avals  # [(shape, dtype)] per flat output leaf
        self.out_treedef = out_treedef
        # the forward closure (primals -> outputs); kept so create_graph
        # backward can re-express this node's pullback as a fresh taped op
        # over (primals, cotangents) — the second-order path
        self.pure_fn = pure_fn
        self.id = next(_seq)

    def release(self):
        self.vjp_fn = None
        self.inputs = []
        self.pure_fn = None


def _accumulate(slot, idx, value):
    cur = slot[idx]
    slot[idx] = value if cur is None else cur + value


def _node_backward_taped(node, full_cots):
    """create_graph path: express this node's pullback as a fresh eager op
    over (primals, cotangents), dispatched through the registry so it is
    itself recorded on the tape (enabling a further backward — any order).
    """
    from ..ops.registry import call_op

    if node.pure_fn is None:
        raise NotImplementedError(
            f"create_graph=True cannot differentiate through op "
            f"'{node.op_name}': it records no forward closure "
            f"(custom PyLayer backwards are first-order only)")
    n_in = len(node.inputs)
    pure_fn = node.pure_fn
    treedef = node.out_treedef

    def bwd(*vals):
        primals, cots = vals[:n_in], vals[n_in:]
        cot_tree = jax.tree_util.tree_unflatten(treedef, list(cots))
        _, vjp_fn = jax.vjp(pure_fn, *primals)
        return tuple(vjp_fn(cot_tree))

    out = call_op(f"grad[{node.op_name}]", bwd,
                  (*node.inputs, *full_cots), {})
    return out if isinstance(out, tuple) else (out,)


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             create_graph: bool = False):
    """Run backward from output tensor(s), accumulating into leaf ``.grad``.

    Mirrors the reference's ``egr::Backward`` semantics
    (paddle/fluid/eager/backward.cc:439): default cotangent of ones for
    scalar outputs, accumulation into leaves, optional graph retention.
    With ``create_graph=True`` the backward computation is itself recorded
    on the tape (higher-order autograd; implies graph retention).
    """
    from ..core.tensor import Tensor  # local import to avoid cycle

    if create_graph:
        retain_graph = True

    def lift(arr):
        return Tensor(arr, stop_gradient=True) if create_graph else arr

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # Seed cotangents.
    pending = {}  # node -> list[Optional[array-or-Tensor]] per output leaf
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g_arr = lift(jnp.ones(t._data.shape, t._data.dtype))
        elif create_graph:
            g_arr = g if isinstance(g, Tensor) else lift(jnp.asarray(g))
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._node
        if node is None:
            _leaf_accumulate(t, g_arr, create_graph)
            continue
        if node not in pending:
            pending[node] = [None] * len(node.out_avals)
        _accumulate(pending[node], t._out_index, g_arr)
        roots.append(node)

    if not roots:
        return

    # Collect reachable subgraph.
    seen = set()
    stack = list(roots)
    nodes = []
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes.append(n)
        for inp in n.inputs:
            if inp._node is not None:
                stack.append(inp._node)
    nodes.sort(key=lambda n: n.id, reverse=True)

    for node in nodes:
        cots = pending.get(node)
        if cots is None or all(c is None for c in cots):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f"trying to backward through op '{node.op_name}' a second "
                "time; set retain_graph=True if you need to")
        # Fill missing output cotangents with zeros.
        full = [c if c is not None else lift(jnp.zeros(shape, dtype))
                for c, (shape, dtype) in zip(cots, node.out_avals)]
        if create_graph:
            in_grads = _node_backward_taped(node, full)
        else:
            cot_tree = jax.tree_util.tree_unflatten(node.out_treedef, full)
            in_grads = node.vjp_fn(cot_tree)
        for inp, g in zip(node.inputs, in_grads):
            g = inp._apply_grad_hooks(g)
            child = inp._node
            if child is None:
                _leaf_accumulate(inp, g, create_graph)
            else:
                if child not in pending:
                    pending[child] = [None] * len(child.out_avals)
                _accumulate(pending[child], inp._out_index, g)
                if inp._retain_grads:
                    _leaf_accumulate(inp, g, create_graph)
        if not retain_graph:
            node.release()
        pending.pop(node, None)


def _leaf_accumulate(t, g_arr, create_graph: bool = False):
    from ..core.tensor import Tensor

    if t.stop_gradient and not t._retain_grads:
        return
    raw = g_arr._data if isinstance(g_arr, Tensor) else g_arr
    if jax.dtypes.result_type(raw) == jax.dtypes.float0:
        return  # integer/bool leaf: jax's symbolic zero cotangent
    if create_graph:
        g_t = g_arr if isinstance(g_arr, Tensor) else Tensor(
            g_arr, stop_gradient=True)
        if g_t._data.dtype != t._data.dtype:
            # same dtype contract as the first-order path; ops.cast keeps
            # the grad-of-grad graph intact
            g_t = g_t.astype(t._data.dtype)
        t._grad = g_t if t._grad is None else t._grad + g_t
        return
    if g_arr.dtype != t._data.dtype:
        g_arr = g_arr.astype(t._data.dtype)
    if t._grad is None:
        t._grad = Tensor(g_arr, stop_gradient=True)
    else:
        t._grad = Tensor(t._grad._data + g_arr, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph: bool = False, only_inputs: bool = True,
         allow_unused: bool = False):
    """Functional gradient API (reference: python/paddle/autograd, `GeneralGrad`
    in paddle/fluid/eager/backward.cc). Returns grads of outputs w.r.t. inputs
    without polluting ``.grad`` of other leaves.
    """
    from ..core.tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if retain_graph is None:
        retain_graph = bool(create_graph)

    # Temporarily stash and clear .grad on the inputs, run backward with
    # retain_grads forced on inputs, then restore.
    saved = [(t, t._grad, t._retain_grads, t.stop_gradient) for t in inputs]
    try:
        for t in inputs:
            t._grad = None
            t._retain_grads = True
        backward(outputs, grad_outputs, retain_graph=retain_graph,
                 create_graph=create_graph)
        results = []
        for t in inputs:
            if t._grad is None and not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient; pass "
                    "allow_unused=True to get None instead")
            results.append(t._grad)
        return results
    finally:
        for t, g, rg, sg in saved:
            t._grad = g
            t._retain_grads = rg
            t.stop_gradient = sg

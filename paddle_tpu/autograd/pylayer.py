"""PyLayer: user-defined autograd functions.

TPU-native analogue of the reference's PyLayer (paddle/fluid/eager/pylayer/,
python/paddle/autograd/py_layer.py): the user writes static forward/backward;
apply() records one GradNode whose pullback calls the user's backward. The
user's math is still framework ops, so a PyLayer nested in jitted code traces
fine in the forward; the custom backward participates only in eager tape
backward (for jit training the functional path uses jax.custom_vjp —
see paddle_tpu.incubate.custom_vjp).
"""
from __future__ import annotations


import jax

from . import tape as _tape


def _tensor_cls():
    from ..core.tensor import Tensor
    return Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.non_differentiable = set()

    def save_for_backward(self, *tensors):
        from . import saved_tensors_hooks
        hooks = saved_tensors_hooks._active
        if hooks is not None:
            # capture the unpack hook at pack time: backward may run
            # after the context manager has exited
            self._saved = tuple(hooks.pack_hook(t) for t in tensors)
            self._unpack = hooks.unpack_hook
        else:
            self._saved = tuple(tensors)
            self._unpack = None

    def saved_tensor(self):
        if getattr(self, "_unpack", None) is not None:
            return tuple(self._unpack(t) for t in self._saved)
        return self._saved

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable.update(id(t) for t in tensors)


class _PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError(
            f"call {cls.__name__}.apply(...), not the class itself")


class PyLayer(metaclass=_PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        Tensor = _tensor_cls()
        ctx = PyLayerContext()
        inputs = [a for a in args if isinstance(a, Tensor)] + \
                 [v for v in kwargs.values() if isinstance(v, Tensor)]
        diff_inputs = [t for t in inputs
                       if (not t.stop_gradient or t._node is not None)]

        with _tape.no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        single = isinstance(out, Tensor)
        outs = [out] if single else list(out)
        need_grad = _tape.grad_enabled() and bool(diff_inputs)
        if need_grad:
            out_avals = [(o._data.shape, o._data.dtype) for o in outs]
            import jax.tree_util as jtu
            _, treedef = jtu.tree_flatten([0] * len(outs))

            def vjp_fn(cotangents):
                Tensor = _tensor_cls()
                grads = [Tensor(g, stop_gradient=True) for g in cotangents]
                with _tape.no_grad():
                    in_grads = cls.backward(ctx, *grads)
                if isinstance(in_grads, Tensor) or in_grads is None:
                    in_grads = (in_grads,)
                result = []
                gi = iter(in_grads)
                for t in diff_inputs:
                    g = next(gi, None)
                    if g is None:
                        import jax.numpy as jnp
                        result.append(jnp.zeros(t._data.shape, t._data.dtype))
                    else:
                        result.append(g._data if isinstance(g, Tensor) else g)
                return tuple(result)

            node = _tape.GradNode(f"pylayer:{cls.__name__}", vjp_fn,
                                  diff_inputs, out_avals, treedef)
            for i, o in enumerate(outs):
                if id(o) not in ctx.non_differentiable:
                    o._node = node
                    o._out_index = i
                    o.stop_gradient = False
        return out if single else type(out)(outs) if isinstance(out, (list, tuple)) else outs


def once_differentiable(fn):
    return fn

"""Functional higher-order autograd: jacobian / hessian / jvp / vjp.

Reference: python/paddle/autograd/ (paddle.autograd.jacobian, hessian,
incubate jvp/vjp). TPU-native: the framework's eager ops are pure JAX
underneath, so a user function over Tensors can be re-traced as a pure
array function and handed to jax.jacrev/jax.hessian — one compiled
computation instead of the reference's row-by-row double-grad loops.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _tensor_cls():
    from ..core.tensor import Tensor  # deferred: tensor imports the tape
    return Tensor


def _as_pure(func: Callable, n: int) -> Callable:
    """Wrap a Tensor->Tensor function as a pure array function (the eager
    ops dispatch fine on traced arrays)."""

    def pure(*arrays):
        Tensor = _tensor_cls()
        tensors = [Tensor(a) for a in arrays]
        out = func(*tensors)
        if isinstance(out, (list, tuple)):
            return type(out)(o._data if isinstance(o, Tensor) else o
                             for o in out)
        return out._data if isinstance(out, Tensor) else out

    return pure


def _unwrap(xs):
    Tensor = _tensor_cls()
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)
    return single, [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                    for x in xs_list]


def _wrap_tree(tree):
    Tensor = _tensor_cls()
    return jax.tree_util.tree_map(
        lambda a: Tensor(a, stop_gradient=True), tree)


def jacobian(func: Callable, xs, create_graph: bool = False):
    """d func / d xs via reverse mode. ``xs``: Tensor or sequence.

    Returns the jacobian pytree (Tensor leaves); for a single input and
    single output this is one Tensor of shape out_shape + in_shape.
    """
    if create_graph:
        raise NotImplementedError(
            "jacobian(create_graph=True) is not supported: the result is "
            "computed functionally and returned detached; differentiate "
            "a function of jacobian via hessian()/jax transforms instead")
    single, arrays = _unwrap(xs)
    jac = jax.jacrev(_as_pure(func, len(arrays)),
                     argnums=tuple(range(len(arrays))))(*arrays)
    jac = jac[0] if single else jac
    return _wrap_tree(jac)


def hessian(func: Callable, xs):
    """d2 func / d xs2 (func must return a scalar)."""
    single, arrays = _unwrap(xs)
    hes = jax.hessian(_as_pure(func, len(arrays)),
                      argnums=tuple(range(len(arrays))))(*arrays)
    hes = hes[0][0] if single else hes
    return _wrap_tree(hes)


def jvp(func: Callable, xs, v=None):
    """Forward-mode JVP (paddle.incubate.autograd.jvp)."""
    single, arrays = _unwrap(xs)
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        _, tangents = _unwrap(v)
    out, tangent_out = jax.jvp(_as_pure(func, len(arrays)),
                               tuple(arrays), tuple(tangents))
    return _wrap_tree(out), _wrap_tree(tangent_out)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode VJP (paddle.incubate.autograd.vjp)."""
    single, arrays = _unwrap(xs)
    out, pull = jax.vjp(_as_pure(func, len(arrays)), *arrays)
    Tensor = _tensor_cls()
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else jnp.asarray(t),
            v, is_leaf=lambda t: isinstance(t, Tensor))
    grads = pull(cot)
    grads = grads[0] if single else grads
    return _wrap_tree(out), _wrap_tree(grads)

"""Autograd public API (reference: python/paddle/autograd/)."""
from .tape import (backward, grad, no_grad, enable_grad, set_grad_enabled,
                   grad_enabled, GradNode)
from .pylayer import PyLayer, PyLayerContext
from .functional import jacobian, hessian, jvp, vjp

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext",
           "jacobian", "hessian", "jvp", "vjp"]


def is_grad_enabled() -> bool:
    return grad_enabled()

"""Autograd public API (reference: python/paddle/autograd/)."""
from .tape import (backward, grad, no_grad, enable_grad, set_grad_enabled,
                   grad_enabled, GradNode)
from .pylayer import PyLayer, PyLayerContext
from .functional import jacobian, hessian, jvp, vjp

__all__ = ["backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
           "is_grad_enabled", "PyLayer", "PyLayerContext",
           "jacobian", "hessian", "jvp", "vjp"]


def is_grad_enabled() -> bool:
    return grad_enabled()


class saved_tensors_hooks:
    """Context manager intercepting activation saves (reference
    autograd/saved_tensors_hooks.py: pack/unpack hooks on the grad
    tape, used for CPU-offload or compression of saved activations).

    The eager tape stores vjp residuals opaquely inside jax pullback
    closures, so per-tensor pack/unpack cannot be applied there; the
    supported contract is the reference's main use case — transforming
    tensors explicitly saved through PyLayerContext.save_for_backward.
    """

    _active = None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        saved_tensors_hooks._active = self
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = None
        return False


__all__ += ["saved_tensors_hooks"]

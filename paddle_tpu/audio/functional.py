"""audio.functional (reference: python/paddle/audio/functional/ —
window functions, mel scale conversions)."""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype="float32") -> Tensor:
    n = win_length
    sym = not fftbins
    m = n - 1 if sym else n
    k = jnp.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * jnp.pi * k / m)
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * jnp.pi * k / m)
    elif window == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * jnp.pi * k / m)
             + 0.08 * jnp.cos(4 * jnp.pi * k / m))
    elif window in ("rect", "boxcar", "ones"):
        w = jnp.ones((n,))
    else:
        raise ValueError(f"unknown window {window!r}")
    return Tensor(w.astype(dtype))


def hz_to_mel(freq, htk: bool = False):
    f = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + f / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    return jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(f / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk: bool = False):
    m = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    return jnp.where(m >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                     freqs)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm="slaney",
                         dtype="float32") -> Tensor:
    f_max = f_max or sr / 2.0
    n_freqs = n_fft // 2 + 1
    fft_freqs = jnp.linspace(0, sr / 2.0, n_freqs)
    mel_pts = jnp.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                           n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    lower = hz_pts[:-2][:, None]
    center = hz_pts[1:-1][:, None]
    upper = hz_pts[2:][:, None]
    up = (fft_freqs[None, :] - lower) / jnp.maximum(center - lower, 1e-8)
    down = (upper - fft_freqs[None, :]) / jnp.maximum(upper - center, 1e-8)
    fb = jnp.maximum(0.0, jnp.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb = fb * enorm[:, None]
    return Tensor(fb.astype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    s = spect.data if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)

"""paddle.audio namespace (reference: python/paddle/audio/ — spectrogram
features + window functions). STFT math rides paddle_tpu.signal."""
from . import functional  # noqa: F401
from . import features  # noqa: F401

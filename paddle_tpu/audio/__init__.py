"""paddle.audio namespace (reference: python/paddle/audio/ — spectrogram
features + window functions). STFT math rides paddle_tpu.signal."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import info, load, save  # noqa: F401

"""audio.features layers (reference: python/paddle/audio/features/layers.py
— Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..signal import stft
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length)

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        return Tensor(jnp.abs(spec.data) ** self.power)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                             htk, norm, dtype)

    def forward(self, x):
        s = self.spectrogram(x)
        return Tensor(jnp.einsum("mf,...ft->...mt", self.fbank.data, s.data))


class LogMelSpectrogram(MelSpectrogram):
    def __init__(self, *args, ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, **kw):
        super().__init__(*args, **kw)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = super().forward(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, **mel_kw):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, **mel_kw)
        self.n_mfcc = n_mfcc

    def forward(self, x):
        logmel = self.log_mel(x).data          # [..., n_mels, T]
        n = logmel.shape[-2]
        k = jnp.arange(self.n_mfcc)[:, None]
        m = jnp.arange(n)[None, :]
        dct = jnp.cos(jnp.pi * k * (2 * m + 1) / (2 * n)) * jnp.sqrt(2.0 / n)
        dct = dct.at[0].multiply(1.0 / jnp.sqrt(2.0))
        return Tensor(jnp.einsum("km,...mt->...kt", dct, logmel))

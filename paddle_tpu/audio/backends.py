"""paddle.audio.backends — wave IO.

Reference: python/paddle/audio/backends/ (wave_backend.py over the
stdlib wave module, plus optional paddleaudio soundfile backends).
This build ships the stdlib wave backend (16/8/32-bit PCM WAV); other
formats need a soundfile install, which zero-egress images lack.
"""
from __future__ import annotations

import wave as _wave
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


class AudioInfo(NamedTuple):
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


_WIDTH_DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r} needs paddleaudio/soundfile "
            "(unavailable in this zero-egress build); wave_backend "
            "handles PCM WAV")


def info(filepath: str) -> AudioInfo:
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8,
                         f"PCM_{'U' if f.getsampwidth() == 1 else 'S'}")


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """WAV -> (waveform [C, T] (or [T, C]), sample_rate)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = num_frames if num_frames >= 0 else f.getnframes() - frame_offset
        raw = f.readframes(n)
    data = np.frombuffer(raw, _WIDTH_DTYPE[width]).reshape(-1, nch)
    if width == 1:
        data = data.astype(np.float32) / 128.0 - 1.0
    elif normalize:
        data = data.astype(np.float32) / float(2 ** (width * 8 - 1))
    out = data.T if channels_first else data
    return Tensor(jnp.asarray(out)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: Optional[int] = 16):
    """waveform (float in [-1,1] or int16) -> PCM WAV."""
    data = np.asarray(src.data if isinstance(src, Tensor) else src)
    if channels_first:
        data = data.T
    if data.ndim == 1:
        data = data[:, None]
    if data.dtype != np.int16:
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(data.tobytes())

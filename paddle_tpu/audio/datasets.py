"""paddle.audio.datasets — audio dataset surface.

Reference: python/paddle/audio/datasets/{tess,esc50}.py — folder-of-wavs
datasets that download archives. Zero-egress build: datasets read an
already-extracted local directory (``data_dir``); the label is the
parent folder name, matching the reference's on-disk layout after its
download step.
"""
from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

from ..io.dataset import Dataset
from .backends import load as _load

__all__ = ["AudioFolderDataset", "TESS", "ESC50"]


class AudioFolderDataset(Dataset):
    """<data_dir>/<label>/<clip>.wav layout -> (waveform, label_idx)."""

    def __init__(self, data_dir: str, sample_rate: int = None,
                 feat_type: str = "raw", **kwargs):
        if not os.path.isdir(data_dir):
            raise FileNotFoundError(
                f"{data_dir!r} not found — place the extracted dataset "
                "there (downloads need egress this build doesn't have)")
        self.files: List[Tuple[str, int]] = []
        labels = sorted(d for d in os.listdir(data_dir)
                        if os.path.isdir(os.path.join(data_dir, d)))
        self.label_list = labels
        for li, lab in enumerate(labels):
            folder = os.path.join(data_dir, lab)
            for f in sorted(os.listdir(folder)):
                if f.lower().endswith(".wav"):
                    self.files.append((os.path.join(folder, f), li))

    def __getitem__(self, idx):
        path, label = self.files[idx]
        wav, _sr = _load(path)
        return np.asarray(wav.data), label

    def __len__(self):
        return len(self.files)


class TESS(AudioFolderDataset):
    """reference audio/datasets/tess.py (Toronto emotional speech set)."""


class ESC50(AudioFolderDataset):
    """reference audio/datasets/esc50.py (environmental sounds)."""

"""paddle.quantization namespace.

Reference: python/paddle/quantization/ (QuantConfig, QAT/PTQ entries,
observers + fake quanters).

TPU-native: simulated quantization (fake-quant in the traced graph, which
XLA fuses into the surrounding ops); int8 deployment depends on the
serving runtime, so this layer's contract is numerics, not storage.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer


def quant_dequant_absmax(x, bits: int = 8, scale=None):
    """Symmetric absmax fake quantization (quanters/abs_max.py)."""
    data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = float(2 ** (bits - 1) - 1)
    if scale is None:
        scale = jnp.maximum(jnp.max(jnp.abs(data)), 1e-8)
    q = jnp.clip(jnp.round(data / scale * qmax), -qmax, qmax)
    out = q * scale / qmax
    return Tensor(out), Tensor(jnp.asarray(scale))


class BaseQuanter(Layer):
    def scales(self):
        return getattr(self, "_scale", None)


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT weight/activation quanter with EMA absmax (reference
    FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype="float32", name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self._scale = None

    def forward(self, x):
        data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        # the EMA scale stays a DEVICE scalar: a float() coercion here
        # would host-sync every training forward (source_lint PT003)
        cur = jnp.maximum(jnp.max(jnp.abs(data)), 1e-8)
        if self.training:
            if self._scale is None:
                self._scale = cur
            else:
                r = self.moving_rate
                self._scale = r * self._scale + (1 - r) * cur
        scale = self._scale if self._scale is not None else cur
        qmax = float(2 ** (self.bit_length - 1) - 1)
        q = jnp.clip(jnp.round(data / scale * qmax), -qmax, qmax)
        # straight-through estimator: forward quantized, grad identity
        out = data + jax.lax.stop_gradient(q * scale / qmax - data)
        return Tensor(out) if isinstance(x, Tensor) else out


class AbsmaxObserver(BaseQuanter):
    """PTQ calibration observer (observers/abs_max.py): tracks the running
    max; quantizes only at convert time."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = jnp.float32(0.0)

    def forward(self, x):
        data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        # running max stays device-side (like ChannelWiseAbsmaxObserver)
        # — no per-observation host sync
        self._scale = jnp.maximum(self._scale,
                                  jnp.max(jnp.abs(data)).astype(jnp.float32))
        return x


class QuantConfig:
    """Maps layer types/instances to (activation, weight) quanters
    (reference quantization/config.py)."""

    def __init__(self, activation=None, weight=None):
        self.global_activation = activation
        self.global_weight = weight
        self._type_configs: Dict[Type, tuple] = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        for t in (layer_types if isinstance(layer_types, (list, tuple))
                  else [layer_types]):
            self._type_configs[t] = (activation, weight)

    def _for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.global_activation, self.global_weight)


class QuantedLinear(Layer):
    """Linear with fake-quantized weight+activation."""

    def __init__(self, linear, a_quanter, w_quanter):
        super().__init__()
        self.inner = linear
        self.a_quanter = a_quanter
        self.w_quanter = w_quanter

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.inner.weight
        if self.w_quanter is not None:
            wq = self.w_quanter(Tensor(w.data))
            saved = w.data
            w.data = wq.data
            try:
                out = self.inner(x)
            finally:
                w.data = saved
            return out
        return self.inner(x)


def _replace_sublayer(model: Layer, dotted_name: str, new_layer: Layer):
    """Swap the sublayer at ``a.b.c`` for ``new_layer``."""
    parent, _, leaf = dotted_name.rpartition(".")
    holder = model
    if parent:
        for part in parent.split("."):
            holder = getattr(holder, part)
    setattr(holder, leaf, new_layer)


class QAT:
    """Quantization-aware training entry (reference qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        from ..nn.modules_basic import Linear
        model = model if inplace else copy.deepcopy(model)
        for name, sub in list(model.named_sublayers()):
            if isinstance(sub, Linear):
                a_cls, w_cls = self.config._for(sub)
                _replace_sublayer(model, name, QuantedLinear(
                    sub, a_cls() if a_cls else None,
                    w_cls() if w_cls else None))
        return model


class ChannelWiseAbsmaxObserver(BaseQuanter):
    """Per-output-channel absmax observer for [in, out] Linear weights
    (reference observers/abs_max_headwise.py / per-channel weight
    observer). Produces one scale per output feature."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def forward(self, x):
        data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        cur = jnp.maximum(jnp.max(jnp.abs(data), axis=0), 1e-8)
        self._scale = (cur if self._scale is None
                       else jnp.maximum(self._scale, cur))
        return x


class Int8Linear(Layer):
    """Deployed weight-only int8 Linear: stores the weight as real int8
    plus a per-output-channel f32 scale and dequantizes into the matmul
    dtype at call time (reference capability: int8 deploy after
    PTQ.convert, quantization/ptq.py). Weight-only is the TPU-relevant
    deployment shape — 2x HBM cut on the weight stream, activations stay
    bf16 for the MXU."""

    def __init__(self, qweight, scales, bias=None, compute_dtype=None):
        super().__init__()
        # buffers, not attributes: state_dict must carry the deployed
        # weights through save/load
        self.register_buffer("qweight", Tensor(qweight))   # int8 [in, out]
        self.register_buffer("scales", Tensor(scales))     # f32 [out]
        self.bias = bias
        self.compute_dtype = compute_dtype or jnp.float32

    def forward(self, x):
        data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        w = (self.qweight.data.astype(self.compute_dtype)
             * (self.scales.data / 127.0).astype(self.compute_dtype))
        out = data.astype(self.compute_dtype) @ w
        if self.bias is not None:
            out = out + self.bias.data.astype(self.compute_dtype)
        return Tensor(out) if isinstance(x, Tensor) else out


def quantize_weight_int8(w):
    """[in, out] float weight -> (int8 weight, f32 per-channel scales)."""
    data = w.data if isinstance(w, Tensor) else jnp.asarray(w)
    scales = jnp.maximum(jnp.max(jnp.abs(data), axis=0), 1e-8)
    q = jnp.clip(jnp.round(data / scales * 127.0), -127, 127).astype(jnp.int8)
    return q, scales.astype(jnp.float32)


class PTQ(QAT):
    """Post-training quantization (reference quantization/ptq.py):
    ``quantize`` wraps layers with observers, the caller runs
    representative data through the model (activation calibration), and
    ``convert`` replaces each observed Linear with an ``Int8Linear``
    holding real int8 storage. Weight scales come from the weight
    quanter's observed per-channel scale when it recorded one (e.g.
    ``ChannelWiseAbsmaxObserver``); otherwise from the weights directly
    — weights are fully known at convert time, so unlike activations
    they need no data pass."""

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        model = model if inplace else copy.deepcopy(model)
        for name, sub in list(model.named_sublayers()):
            if not isinstance(sub, QuantedLinear):
                continue
            w = sub.inner.weight
            observed = getattr(sub.w_quanter, "_scale", None)
            if (observed is not None and getattr(observed, "ndim", 0) == 1
                    and observed.shape[0] == w.data.shape[-1]):
                scales = jnp.asarray(observed, jnp.float32)
                q = jnp.clip(jnp.round(w.data / scales * 127.0),
                             -127, 127).astype(jnp.int8)
            else:
                q, scales = quantize_weight_int8(w)
            _replace_sublayer(model, name, Int8Linear(
                q, scales, bias=sub.inner.bias,
                compute_dtype=w.data.dtype))
        return model


# functional-pytree PTQ for the decode stacks (llama / qwen2_moe):
# weight-only int8 deploy, the TPU counterpart of ptq.py convert +
# cutlass weight-only GEMMs
from .decode import (  # noqa: E402
    decode_weight_bytes,
    dequantize_for_decode,
    is_quantized_params,
    quantize_for_decode,
)

BaseObserver = BaseQuanter  # reference factory.py: observers are quanters


class _QuanterFactory:
    """reference quantization/factory.py quanter(): wraps a quanter
    class so QuantConfig can hold partially-applied constructors."""

    def __init__(self, cls, *args, **kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.cls(*self.args, **self.kwargs)

    def __call__(self, *args, **kwargs):
        return self.cls(*(args or self.args), **(kwargs or self.kwargs))


def quanter(name=None):
    """Class decorator registering a quanter under ``name`` and giving
    it a partial-application helper (reference @quanter('FakeQuanter...'))."""
    def deco(cls):
        cls.partial = classmethod(
            lambda c, *a, **k: _QuanterFactory(c, *a, **k))
        return cls
    return deco

"""Post-training weight-only int8 quantization of the decode models.

Reference capability: the PTQ-deploy pipeline (python/paddle/quantization/
ptq.py convert + the int8 weight-only GEMMs it deploys onto). The layer
quantizers in ``paddle_tpu.quantization`` operate on ``nn.Layer`` models;
THIS module is the functional-pytree counterpart for the flagship decode
stacks (models/llama.py, models/qwen2_moe.py), whose params are plain
pytrees consumed by ``lax.scan``.

``quantize_for_decode(params, cfg)`` replaces every matmul projection
that dominates decode's weight stream with an
``ops.fused.int8_matmul.Int8Weight`` (symmetric int8 + per-output-channel
f32 scale, one scale per (layer[, expert], out_channel)):

  llama:     wq wk wv wo w_gate w_up w_down, lm_head
  qwen2_moe: wq wk wv wo, routed experts w_gate/w_up/w_down,
             shared expert w_gate/w_up/w_down, lm_head

Deliberately NOT quantized:
  * embed — consumed by row lookup, not matmul; one row (D·2 bytes) per
    step is already negligible traffic;
  * norms (attn/mlp/final) — O(D) vectors;
  * qwen's router — kept f32 by design for stable top-k softmax (a
    routing flip is a much larger numeric event than a logit wobble),
    and it is O(D·E) — noise traffic;
  * qwen's shared-expert sigmoid gate — O(D·1).

The quantized pytree drops into every decode entry point unchanged —
``generate``, ``generate_paged``, ``serving_prefill`` /
``serving_decode_step`` / ``serving_decode_block`` — because the model
bodies dispatch matmuls through ``_mm`` (dense array or Int8Weight).
Training paths are out of scope: quantize AFTER training, for serving.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from ..ops.fused.int8_matmul import Int8Weight

__all__ = ["quantize_for_decode", "dequantize_for_decode",
           "is_quantized_params", "decode_weight_bytes"]

_LLAMA_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
_QWEN_ATTN_KEYS = ("wq", "wk", "wv", "wo")
_FFN_KEYS = ("w_gate", "w_up", "w_down")


def _is_moe(cfg) -> bool:
    return hasattr(cfg, "num_experts")


def quantize_for_decode(params: Dict[str, Any], cfg, *,
                        quantize_lm_head: bool = True) -> Dict[str, Any]:
    """params (llama- or qwen2_moe-family pytree) -> a new pytree whose
    projection weights are ``Int8Weight``s. Model family comes from the
    config shape (``num_experts`` present = MoE). Idempotent-hostile by
    design: quantizing an already-quantized tree raises (re-quantizing
    int8 through f32 would silently double the error)."""
    if is_quantized_params(params):
        raise ValueError("params are already weight-only quantized")
    layers = dict(params["layers"])
    if _is_moe(cfg):
        for k in _QWEN_ATTN_KEYS:
            layers[k] = Int8Weight.quantize(layers[k])
        experts = dict(layers["experts"])
        for k in _FFN_KEYS:
            # [L, E, D, F]: per-(layer, expert, out-channel) scales
            experts[k] = Int8Weight.quantize(experts[k])
        layers["experts"] = experts
        shared = dict(layers["shared"])
        for k in _FFN_KEYS:
            shared[k] = Int8Weight.quantize(shared[k])
        layers["shared"] = shared
    else:
        for k in _LLAMA_LAYER_KEYS:
            layers[k] = Int8Weight.quantize(layers[k])
    out = dict(params, layers=layers)
    if quantize_lm_head:
        out["lm_head"] = Int8Weight.quantize(params["lm_head"])
    return out


def dequantize_for_decode(params: Dict[str, Any],
                          dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Inverse structural map: every Int8Weight becomes its dense
    ``dtype`` approximation (for A/B numerics, not a bit-exact undo)."""
    def walk(node):
        if isinstance(node, Int8Weight):
            return node.dequant(dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node
    return walk(params)


def is_quantized_params(params) -> bool:
    def any_q(node) -> bool:
        if isinstance(node, Int8Weight):
            return True
        if isinstance(node, dict):
            return any(any_q(v) for v in node.values())
        return False
    return any_q(params)


def decode_weight_bytes(params) -> int:
    """HBM bytes the decode step streams for weights: every leaf's
    nbytes (int8 q + f32 scales for quantized, full dtype otherwise),
    EXCEPT the embedding table — decode reads one row per token, so the
    table's size is not per-step traffic (its row is counted instead)."""
    import numpy as np

    def leaf_bytes(node) -> int:
        if isinstance(node, Int8Weight):
            return int(node.q.size) * 1 + int(node.scale.size) * 4
        if isinstance(node, dict):
            return sum(leaf_bytes(v) for v in node.values())
        if hasattr(node, "size") and hasattr(node, "dtype"):
            return int(node.size) * np.dtype(node.dtype).itemsize
        return 0

    total = sum(leaf_bytes(v) for k, v in params.items() if k != "embed")
    emb = params.get("embed")
    if emb is not None:
        # one row lookup per decode step
        total += int(emb.shape[-1]) * np.dtype(emb.dtype).itemsize
    return total

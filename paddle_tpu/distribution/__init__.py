"""paddle.distribution namespace.

Reference: python/paddle/distribution/ (20+ distributions with
sample/rsample/log_prob/entropy/kl_divergence over a Distribution base,
kl.py registration).

TPU-native: math in jnp (traceable under jit), sampling via jax.random
with an internal key threaded from the global generator (core/generator.py)
so eager sampling stays reproducible under paddle_tpu.seed().
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.generator import default_generator


def _u(x):
    if isinstance(x, Tensor):
        return x.data
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


def _key():
    return default_generator().next_key()


def _shape(sample_shape) -> tuple:
    if sample_shape is None:
        return ()
    return tuple(int(s) for s in sample_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_u(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _u(loc)
        self.scale = _u(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        eps = jax.random.normal(_key(), shp)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _u(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))

    def cdf(self, value):
        v = _u(value)
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (v - self.loc) / (self.scale * math.sqrt(2)))))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _u(low)
        self.high = _u(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _u(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _u(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _u(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            _key(), self.probs, shp).astype(jnp.float32))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(v * jax.nn.log_sigmoid(self.logits)
                      + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = jax.nn.log_softmax(_u(logits), axis=-1)
        else:
            self.logits = jnp.log(_u(probs) /
                                  jnp.sum(_u(probs), -1, keepdims=True))
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.categorical(_key(), self.logits,
                                             shape=shp))

    def log_prob(self, value):
        v = _u(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            self.logits, v[..., None], axis=-1)[..., 0])

    def entropy(self):
        return Tensor(-jnp.sum(self.probs * self.logits, axis=-1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _u(rate)
        super().__init__(self.rate.shape)

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.exponential(_key(), shp) / self.rate)

    def log_prob(self, value):
        v = _u(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _u(concentration)
        self.rate = _u(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.gamma(_key(), self.concentration, shp)
                      / self.rate)

    def log_prob(self, value):
        v = _u(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return Tensor(a - jnp.log(b) + jax.scipy.special.gammaln(a)
                      + (1 - a) * jax.scipy.special.digamma(a))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _u(alpha)
        self.beta = _u(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta, shp))

    def log_prob(self, value):
        v = _u(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _u(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(_key(), self.concentration, shp))

    def log_prob(self, value):
        v = _u(value)
        a = self.concentration
        lognorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                   - jax.scipy.special.gammaln(jnp.sum(a, -1)))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - lognorm)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _u(loc)
        self.scale = _u(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.laplace(_key(), shp))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _u(loc)
        self.scale = _u(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(_key(), shp))

    def log_prob(self, value):
        z = (_u(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    def rsample(self, shape=()):
        return Tensor(jnp.exp(_u(self._normal.rsample(shape))))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(_u(self._normal.log_prob(jnp.log(v))) - jnp.log(v))


class Multinomial(Distribution):
    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _u(probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            _key(), logits, shape=(self.total_count,) + shp)
        k = self.probs.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=0)
        return Tensor(counts)

    def log_prob(self, value):
        v = _u(value)
        logfact = jax.scipy.special.gammaln(v + 1)
        return Tensor(jax.scipy.special.gammaln(
            jnp.asarray(self.total_count + 1.0))
            - jnp.sum(logfact, -1)
            + jnp.sum(v * jnp.log(self.probs), -1))


# ---------------------------------------------------------------------------
# KL divergence registry (reference: distribution/kl.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    return Tensor(jnp.sum(p.probs * (p.logits - q.logits), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    a = p.probs * (jnp.log(p.probs) - jnp.log(q.probs))
    b = (1 - p.probs) * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs))
    return Tensor(a + b)


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


class Poisson(Distribution):
    """reference distribution/poisson.py."""

    def __init__(self, rate, name=None):
        self.rate = jnp.asarray(_u(rate), jnp.float32)
        super().__init__(jnp.shape(self.rate))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.poisson(_key(), self.rate, shp).astype(
            jnp.float32))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(v * jnp.log(self.rate) - self.rate
                      - jax.scipy.special.gammaln(v + 1))

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def entropy(self):
        # series approximation (reference uses the same truncation idea)
        r = self.rate
        return Tensor(0.5 * jnp.log(2 * jnp.pi * jnp.e * r)
                      - 1 / (12 * r) - 1 / (24 * r ** 2))


class Binomial(Distribution):
    """reference distribution/binomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(_u(total_count), jnp.float32)
        self.probs = jnp.asarray(_u(probs), jnp.float32)
        super().__init__(jnp.broadcast_shapes(jnp.shape(self.total_count),
                                              jnp.shape(self.probs)))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.binomial(_key(), self.total_count,
                                          self.probs, shp))

    def log_prob(self, value):
        v = _u(value)
        n, p = self.total_count, self.probs
        comb = (jax.scipy.special.gammaln(n + 1)
                - jax.scipy.special.gammaln(v + 1)
                - jax.scipy.special.gammaln(n - v + 1))
        return Tensor(comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))


class Geometric(Distribution):
    """reference distribution/geometric.py (trials until first success,
    support {0, 1, ...})."""

    def __init__(self, probs, name=None):
        self.probs = jnp.asarray(_u(probs), jnp.float32)
        super().__init__(jnp.shape(self.probs))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_key(), shp, minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    def entropy(self):
        p = self.probs
        return Tensor(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)


class Cauchy(Distribution):
    """reference distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(_u(loc), jnp.float32)
        self.scale = jnp.asarray(_u(scale), jnp.float32)
        super().__init__(jnp.broadcast_shapes(jnp.shape(self.loc),
                                              jnp.shape(self.scale)))

    def rsample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale * jax.random.cauchy(_key(), shp))

    def log_prob(self, value):
        v = _u(value)
        z = (v - self.loc) / self.scale
        return Tensor(-jnp.log(jnp.pi * self.scale * (1 + z * z)))

    def cdf(self, value):
        v = _u(value)
        return Tensor(jnp.arctan((v - self.loc) / self.scale) / jnp.pi + 0.5)

    def entropy(self):
        return Tensor(jnp.log(4 * jnp.pi * self.scale)
                      * jnp.ones(self._batch_shape))


class Chi2(Distribution):
    """reference distribution/chi2.py: Gamma(df/2, 1/2)."""

    def __init__(self, df, name=None):
        self.df = jnp.asarray(_u(df), jnp.float32)
        super().__init__(jnp.shape(self.df))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(2.0 * jax.random.gamma(_key(), self.df / 2.0, shp))

    def log_prob(self, value):
        v = _u(value)
        k = self.df / 2.0
        return Tensor((k - 1) * jnp.log(v) - v / 2 - k * jnp.log(2.0)
                      - jax.scipy.special.gammaln(k))

    @property
    def mean(self):
        return Tensor(self.df)

    @property
    def variance(self):
        return Tensor(2 * self.df)


class StudentT(Distribution):
    """reference distribution/student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = jnp.asarray(_u(df), jnp.float32)
        self.loc = jnp.asarray(_u(loc), jnp.float32)
        self.scale = jnp.asarray(_u(scale), jnp.float32)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.df), jnp.shape(self.loc), jnp.shape(self.scale)))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.t(_key(), self.df, shp))

    def log_prob(self, value):
        v = _u(value)
        z = (v - self.loc) / self.scale
        nu = self.df
        lg = jax.scipy.special.gammaln
        return Tensor(lg((nu + 1) / 2) - lg(nu / 2)
                      - 0.5 * jnp.log(nu * jnp.pi) - jnp.log(self.scale)
                      - (nu + 1) / 2 * jnp.log1p(z * z / nu))

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        return Tensor(jnp.where(self.df > 2,
                                self.scale ** 2 * self.df / (self.df - 2),
                                jnp.nan))


class ContinuousBernoulli(Distribution):
    """reference distribution/continuous_bernoulli.py (Loaiza-Ganem &
    Cunningham 2019)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = jnp.asarray(_u(probs), jnp.float32)
        self._lims = lims
        super().__init__(jnp.shape(self.probs))

    def _log_C(self):
        p = self.probs
        near_half = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near_half, 0.25, p)
        logC = jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * safe))
                       / jnp.abs(1 - 2 * safe))
        # taylor at p=1/2: log 2 + 4/3 (p-1/2)^2
        x = p - 0.5
        taylor = jnp.log(2.0) + 4.0 / 3.0 * x * x
        return jnp.where(near_half, taylor, logC)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_key(), shp, minval=1e-6, maxval=1 - 1e-6)
        p = self.probs
        near_half = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near_half, 0.25, p)
        icdf = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return Tensor(jnp.where(near_half, u, icdf))

    def log_prob(self, value):
        v = _u(value)
        p = self.probs
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                      + self._log_C())

    @property
    def mean(self):
        p = self.probs
        near_half = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near_half, 0.25, p)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return Tensor(jnp.where(near_half, 0.5 + (p - 0.5) / 3.0, m))


class MultivariateNormal(Distribution):
    """reference distribution/multivariate_normal.py."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = jnp.asarray(_u(loc), jnp.float32)
        if scale_tril is not None:
            self.scale_tril = jnp.asarray(_u(scale_tril), jnp.float32)
        elif covariance_matrix is not None:
            self.scale_tril = jnp.linalg.cholesky(
                jnp.asarray(_u(covariance_matrix), jnp.float32))
        else:
            raise ValueError("need covariance_matrix or scale_tril")
        super().__init__(jnp.shape(self.loc)[:-1], jnp.shape(self.loc)[-1:])

    def rsample(self, shape=()):
        shp = tuple(shape) + self._batch_shape + self._event_shape
        eps = jax.random.normal(_key(), shp)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i",
                                            self.scale_tril, eps))

    def log_prob(self, value):
        v = _u(value)
        d = self._event_shape[0]
        diff = v - self.loc
        sol = jax.scipy.linalg.solve_triangular(self.scale_tril, diff[..., None],
                                                lower=True)[..., 0]
        logdet = jnp.sum(jnp.log(jnp.abs(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1))), axis=-1)
        return Tensor(-0.5 * (d * jnp.log(2 * jnp.pi)
                              + (sol * sol).sum(-1)) - logdet)

    @property
    def mean(self):
        return Tensor(self.loc)

    def entropy(self):
        d = self._event_shape[0]
        logdet = jnp.sum(jnp.log(jnp.abs(jnp.diagonal(
            self.scale_tril, axis1=-2, axis2=-1))), axis=-1)
        return Tensor(0.5 * d * (1 + jnp.log(2 * jnp.pi)) + logdet)


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference
    distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank, name=None):
        self.base = base
        self.rank = reinterpreted_batch_rank
        nb = len(base.batch_shape) - reinterpreted_batch_rank
        super().__init__(base.batch_shape[:nb],
                         base.batch_shape[nb:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        # base.log_prob already reduced the base's event dims, so its
        # output shape is base.batch_shape; sum the reinterpreted tail
        lp = _u(self.base.log_prob(value))
        return Tensor(lp.sum(axis=tuple(range(-self.rank, 0))))

    def entropy(self):
        e = _u(self.base.entropy())
        axes = tuple(range(-self.rank, 0))
        return Tensor(e.sum(axis=axes))


class ExponentialFamily(Distribution):
    """Base marker class (reference distribution/exponential_family.py):
    provides entropy via the Bregman identity for subclasses that
    define natural parameters. Concrete families here implement entropy
    directly; the class exists for isinstance checks and subclassing."""


class TransformedDistribution(Distribution):
    """reference distribution/transformed_distribution.py: pushforward
    of a base distribution through a chain of transforms."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = value
        log_det = 0.0
        for t in reversed(self.transforms):
            x = t.inverse(y)
            log_det = log_det + _u(t.forward_log_det_jacobian(x))
            y = x
        return Tensor(_u(self.base.log_prob(y)) - log_det)


class LKJCholesky(Distribution):
    """reference distribution/lkj_cholesky.py: distribution over
    Cholesky factors of correlation matrices (LKJ 2009), onion-method
    sampler."""

    def __init__(self, dim, concentration=1.0, sample_method="onion",
                 name=None):
        self.dim = int(dim)
        self.concentration = float(_u(concentration))
        super().__init__((), (self.dim, self.dim))

    def sample(self, shape=()):
        d = self.dim
        eta = self.concentration
        shp = tuple(shape)
        # onion method: build row by row from beta marginals
        L = jnp.zeros(shp + (d, d))
        L = L.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            beta = jax.random.beta(_key(), i / 2.0,
                                   eta + (d - 1 - i) / 2.0, shp)
            u = jax.random.normal(_key(), shp + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(beta)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.maximum(1 - beta, 1e-12)))
        return Tensor(L)

    def log_prob(self, value):
        L = _u(value)
        d = self.dim
        eta = self.concentration
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        orders = jnp.asarray([d - 2 - i + 2 * (eta - 1) for i in range(d - 1)])
        unnorm = (orders * jnp.log(diag)).sum(-1)
        # normalization (reference lkj_cholesky.py log_normalizer)
        lg = jax.scipy.special.gammaln
        idx = jnp.arange(1, d)
        logn = jnp.sum(0.5 * idx * jnp.log(jnp.pi)
                       + lg(eta + (d - 1 - idx) / 2)
                       - lg(eta + (d - 1) / 2))
        return Tensor(unnorm - logn)

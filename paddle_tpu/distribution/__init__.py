"""paddle.distribution namespace.

Reference: python/paddle/distribution/ (20+ distributions with
sample/rsample/log_prob/entropy/kl_divergence over a Distribution base,
kl.py registration).

TPU-native: math in jnp (traceable under jit), sampling via jax.random
with an internal key threaded from the global generator (core/generator.py)
so eager sampling stays reproducible under paddle_tpu.seed().
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.generator import default_generator


def _u(x):
    if isinstance(x, Tensor):
        return x.data
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


def _key():
    return default_generator().next_key()


def _shape(sample_shape) -> tuple:
    if sample_shape is None:
        return ()
    return tuple(int(s) for s in sample_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_u(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _u(loc)
        self.scale = _u(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        eps = jax.random.normal(_key(), shp)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _u(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))

    def cdf(self, value):
        v = _u(value)
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (v - self.loc) / (self.scale * math.sqrt(2)))))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _u(low)
        self.high = _u(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _u(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _u(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _u(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            _key(), self.probs, shp).astype(jnp.float32))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(v * jax.nn.log_sigmoid(self.logits)
                      + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = jax.nn.log_softmax(_u(logits), axis=-1)
        else:
            self.logits = jnp.log(_u(probs) /
                                  jnp.sum(_u(probs), -1, keepdims=True))
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.categorical(_key(), self.logits,
                                             shape=shp))

    def log_prob(self, value):
        v = _u(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            self.logits, v[..., None], axis=-1)[..., 0])

    def entropy(self):
        return Tensor(-jnp.sum(self.probs * self.logits, axis=-1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _u(rate)
        super().__init__(self.rate.shape)

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.exponential(_key(), shp) / self.rate)

    def log_prob(self, value):
        v = _u(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _u(concentration)
        self.rate = _u(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.gamma(_key(), self.concentration, shp)
                      / self.rate)

    def log_prob(self, value):
        v = _u(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return Tensor(a - jnp.log(b) + jax.scipy.special.gammaln(a)
                      + (1 - a) * jax.scipy.special.digamma(a))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _u(alpha)
        self.beta = _u(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta, shp))

    def log_prob(self, value):
        v = _u(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _u(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(_key(), self.concentration, shp))

    def log_prob(self, value):
        v = _u(value)
        a = self.concentration
        lognorm = (jnp.sum(jax.scipy.special.gammaln(a), -1)
                   - jax.scipy.special.gammaln(jnp.sum(a, -1)))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - lognorm)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _u(loc)
        self.scale = _u(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.laplace(_key(), shp))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _u(loc)
        self.scale = _u(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(_key(), shp))

    def log_prob(self, value):
        z = (_u(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    def rsample(self, shape=()):
        return Tensor(jnp.exp(_u(self._normal.rsample(shape))))

    def log_prob(self, value):
        v = _u(value)
        return Tensor(_u(self._normal.log_prob(jnp.log(v))) - jnp.log(v))


class Multinomial(Distribution):
    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _u(probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            _key(), logits, shape=(self.total_count,) + shp)
        k = self.probs.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=0)
        return Tensor(counts)

    def log_prob(self, value):
        v = _u(value)
        logfact = jax.scipy.special.gammaln(v + 1)
        return Tensor(jax.scipy.special.gammaln(
            jnp.asarray(self.total_count + 1.0))
            - jnp.sum(logfact, -1)
            + jnp.sum(v * jnp.log(self.probs), -1))


# ---------------------------------------------------------------------------
# KL divergence registry (reference: distribution/kl.py)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    return Tensor(jnp.sum(p.probs * (p.logits - q.logits), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    a = p.probs * (jnp.log(p.probs) - jnp.log(q.probs))
    b = (1 - p.probs) * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs))
    return Tensor(a + b)


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)

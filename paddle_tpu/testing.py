"""Virtual-device helpers for tests and dry-runs.

Mirrors the reference's fake-device testing pattern (SURVEY.md §4: the
custom_cpu plugin masquerading as a device, test/custom_runtime/): here the
fake devices are XLA host-platform devices, so multi-chip sharding code
paths (pjit/shard_map/collectives) execute for real without TPU hardware.
"""
import os
import re


def force_host_cpu_devices(n: int) -> None:
    """Force JAX onto ``n`` virtual CPU devices, pre-backend-init.

    Process-global and irreversible by design: callers are dedicated test /
    dry-run processes, never a process that later needs the real chip.

    Some sandboxes pin JAX_PLATFORMS to a TPU tunnel and pre-import jax from
    sitecustomize, so env vars alone are read too late — the platform must
    be forced via jax.config before the (lazy) backend initialisation, while
    XLA_FLAGS is still honoured at client creation.
    """
    xla_flags = os.environ.get("XLA_FLAGS", "")
    xla_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       xla_flags)
    os.environ["XLA_FLAGS"] = (
        xla_flags + f" --xla_force_host_platform_device_count={n}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    backend, ndev = jax.default_backend(), len(jax.devices())
    if backend != "cpu" or ndev != n:
        raise RuntimeError(
            f"could not force {n} virtual CPU devices (got backend="
            f"{backend!r}, {ndev} devices) — a JAX backend was already "
            "initialised in this process; call force_host_cpu_devices() "
            "before any jax operation")

"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exporting
tensor/linalg.py). The implementations live in ops/linalg.py."""
from ..ops.linalg import (  # noqa: F401
    matmul, mm, bmm, dot, mv, t, einsum, norm, vector_norm, matrix_norm,
    dist, cholesky, cholesky_solve, inverse, pinv, matrix_rank, matrix_power,
    det, slogdet, qr, svd, svdvals, eig, eigh, eigvals, eigvalsh, solve,
    triangular_solve, lstsq, lu, matrix_exp, multi_dot, corrcoef, cov,
    histogram, bincount, cond, cholesky_inverse, lu_unpack,
    householder_product, ormqr, svd_lowrank, pca_lowrank,
)

inv = inverse

"""paddle_tpu.utils — extension loading and misc utilities
(reference: python/paddle/utils/)."""
from . import cpp_extension

__all__ = ["cpp_extension"]

"""Out-of-tree custom C++ kernels — the custom-op / custom-kernel C API.

Reference capability: paddle.utils.cpp_extension (load/setup compiling
user .cc into ops) and the custom-kernel C API (paddle/phi/capi/): users
ship kernels the framework dispatches without rebuilding it.

TPU-native redesign: the stable plugin ABI is XLA's FFI. ``load()``
compiles user C++ written against the header-only ``xla/ffi/api/ffi.h``
(shipped inside jaxlib — ``jax.ffi.include_dir()``), registers every
exported ``XLA_FFI_DEFINE_HANDLER_SYMBOL`` with jax, and wraps each as a
REGISTERED framework op, so custom kernels dispatch exactly like
built-ins (eager tape, jit, vjp via ``define_grad``). Host kernels run
through the FFI on CPU; on-device TPU kernels are written as Pallas
(ops/pallas) — the FFI path is the host-custom-call half of the
reference's plugin story.

Example (see tests/test_cpp_extension.py for a full kernel)::

    ext = load(name="my_ops", sources=["my_ops.cc"],
               functions={"scaled_add": dict(
                   handler="ScaledAdd", n_args=2,
                   attrs={"alpha": np.float32})})
    y = ext.scaled_add(x1, x2, alpha=2.0)   # a paddle_tpu op
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import types
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np
import jax


def include_paths() -> list:
    """Compiler include dirs for writing FFI kernels."""
    return [jax.ffi.include_dir()]


def _compile(name: str, sources: Sequence[str], extra_cflags, build_dir):
    build_dir = build_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    so = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    if os.path.exists(so) and all(
            os.path.getmtime(so) >= os.path.getmtime(s) for s in srcs):
        return so
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
           *[f"-I{p}" for p in include_paths()],
           *(extra_cflags or []), *srcs, "-o", so]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"custom op build failed:\n{' '.join(cmd)}\n{proc.stderr}")
    return so


def load(name: str, sources: Sequence[str],
         functions: Dict[str, Dict[str, Any]],
         extra_cflags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None,
         platform: str = "cpu"):
    """Compile + register custom FFI kernels; returns a module-like
    namespace of framework ops.

    functions: op_name -> spec with keys
      handler: exported XLA_FFI_DEFINE_HANDLER_SYMBOL name;
      n_args: number of array inputs;
      attrs: optional {attr_name: np dtype} scalar attributes;
      out_like: index of the input whose shape/dtype the output copies
        (default 0), or a callable (*avals) -> ShapeDtypeStruct.
    """
    from ..ops.registry import register_op

    so = _compile(name, sources, extra_cflags, build_directory)
    lib = ctypes.CDLL(so)
    ext = types.SimpleNamespace(__name__=name, _lib=lib, _path=so)

    for op_name, spec in functions.items():
        handler = getattr(lib, spec["handler"])
        target = f"{name}.{op_name}"
        jax.ffi.register_ffi_target(
            target, jax.ffi.pycapsule(handler), platform=platform)
        n_args = int(spec.get("n_args", 1))
        attr_types = spec.get("attrs", {})
        out_like = spec.get("out_like", 0)

        def make(target=target, n_args=n_args, attr_types=attr_types,
                 out_like=out_like, op_name=op_name):
            def fn(*args, **kwargs):
                arrays = args[:n_args]
                attrs = {}
                for k, tp in attr_types.items():
                    if k not in kwargs:
                        raise TypeError(f"{op_name} missing attr {k!r}")
                    attrs[k] = tp(kwargs[k])
                if callable(out_like):
                    out = out_like(*arrays)
                else:
                    ref = arrays[out_like]
                    out = jax.ShapeDtypeStruct(ref.shape, ref.dtype)
                return jax.ffi.ffi_call(target, out)(*arrays, **attrs)

            fn.__name__ = op_name
            return fn

        wrapped = register_op(name=f"{name}.{op_name}",
                              differentiable=False,
                              also_method=False)(make())
        setattr(ext, op_name, wrapped)
    return ext


def define_grad(ext, op_name: str, grad_fn: Callable):
    """Attach a gradient to a loaded custom op: ``grad_fn`` is a pure
    JAX function with the same signature returning the primal output —
    it becomes the differentiable surrogate whose vjp the tape records,
    while the FFI kernel stays the forward implementation under
    ``no_grad``/inference. (The reference's custom-op grad kernels map
    to this: one more function, not another ABI.)"""
    from ..ops.registry import register_op
    from ..autograd import tape as _tape

    fwd = getattr(ext, op_name)

    def op(*args, **kwargs):
        return grad_fn(*args, **kwargs)

    op.__name__ = f"{op_name}_diff"
    diff_inner = register_op(name=f"{ext.__name__}.{op_name}_diff",
                             also_method=False)(op)

    def dispatch(*args, **kwargs):
        # honour the documented contract: the FFI kernel IS the forward
        # when no gradient is needed; the surrogate only runs when the
        # tape must record a differentiable computation
        if not _tape.grad_enabled():
            return fwd(*args, **kwargs)
        return diff_inner(*args, **kwargs)

    dispatch.__name__ = f"{op_name}_diff"
    setattr(ext, op_name + "_diff", dispatch)
    return dispatch

"""paddle.sparse.nn — sparse conv / norm / activation / attention.

Reference: python/paddle/sparse/nn/ (layer/conv.py Conv3D/SubmConv3D over
paddle/phi/kernels/sparse/gpu/conv_kernel.cu, layer/norm.py BatchNorm,
functional/transformer.py attention over
paddle/phi/kernels/sparse/gpu/fused_attention_kernel.cu).

TPU-native design, not a translation:
- The reference's conv builds a GPU hash table (coords -> row) and
  gathers per kernel offset. A hash table is hostile to XLA (dynamic
  probing loops); here the coord->row map is a DENSE int32 grid
  [N, D, H, W] built by one scatter. Voxel grids sparse conv is used on
  (point clouds) have bounded extents, so the grid is cheap, and every
  per-offset step becomes a static gather + matmul — MXU-shaped.
- Sparse attention keeps the CSR pattern as (rows, cols) index streams
  and runs a segment-softmax (segment_max/segment_sum over the row id),
  so only the nnz logits are ever materialized — the same memory
  contract as the reference's fused kernel.
- Regular (non-submanifold) conv generates output coordinates on host
  at call time (data-dependent nnz is a *creation* operation, like
  sparse_coo_tensor); all value compute stays traced.
"""
from __future__ import annotations

import itertools
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn import initializer as I
from . import SparseCooTensor, SparseCsrTensor


def _triple(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v, v)


def _coord_grid(idx: jnp.ndarray, spatial: Sequence[int]) -> jnp.ndarray:
    """Scatter rows into a dense [N, D, H, W] int32 map; empty = -1."""
    grid = jnp.full(spatial, -1, jnp.int32)
    return grid.at[tuple(idx[:, i] for i in range(idx.shape[1]))].set(
        jnp.arange(idx.shape[0], dtype=jnp.int32))


def _gather_neighbors(values, idx, grid, offset, spatial):
    """Rows of `values` at coords idx+offset (zeros where absent)."""
    nbr = idx.at[:, 1:].add(jnp.asarray(offset, idx.dtype))
    ok = jnp.ones((idx.shape[0],), bool)
    for i in range(1, 4):
        ok &= (nbr[:, i] >= 0) & (nbr[:, i] < spatial[i])
    nbr = jnp.clip(nbr, 0, jnp.asarray(spatial, idx.dtype) - 1)
    rows = grid[tuple(nbr[:, i] for i in range(4))]
    ok &= rows >= 0
    gathered = values[jnp.clip(rows, 0, values.shape[0] - 1)]
    return jnp.where(ok[:, None], gathered, 0.0)


def subm_conv3d(x: SparseCooTensor, weight, bias=None, stride=1, padding=0,
                dilation=1) -> SparseCooTensor:
    """Submanifold sparse conv: output coords == input coords (reference
    phi/kernels/sparse/gpu/conv_kernel.cu subm path). weight is
    [kd, kh, kw, in, out] (the reference's DHWCO layout).

    Submanifold semantics fix stride=1 and the kernel centered on each
    site (padding only gates border neighbors, which the validity mask
    already does) — non-default stride/dilation are rejected rather
    than silently ignored."""
    if _triple(stride) != (1, 1, 1) or _triple(dilation) != (1, 1, 1):
        raise ValueError(
            "subm_conv3d requires stride=1, dilation=1 (output sites are "
            "the input sites); use sparse.nn.conv3d for strided conv")
    w = weight.data if isinstance(weight, Tensor) else jnp.asarray(weight)
    kd, kh, kw, cin, cout = w.shape
    idx = jnp.asarray(x._sp.indices, jnp.int32)       # [nnz, 4] n,d,h,w
    vals = x._sp.data                                  # [nnz, cin]
    spatial = tuple(int(s) for s in x.shape[:4])
    grid = _coord_grid(idx, spatial)
    center = (kd // 2, kh // 2, kw // 2)
    out = jnp.zeros((vals.shape[0], cout), w.dtype)
    for od, oh, ow in itertools.product(range(kd), range(kh), range(kw)):
        offset = (od - center[0], oh - center[1], ow - center[2])
        nbr_vals = _gather_neighbors(vals, idx, grid, offset, spatial)
        out = out + nbr_vals.astype(w.dtype) @ w[od, oh, ow]
    if bias is not None:
        b = bias.data if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + b
    return SparseCooTensor(jsparse.BCOO((out, idx), shape=x.shape[:4] + (cout,)))


def conv3d(x: SparseCooTensor, weight, bias=None, stride=1, padding=0,
           dilation=1) -> SparseCooTensor:
    """Regular sparse conv: every kernel tap of every input point emits
    an output site (reference conv_kernel.cu non-subm path). Output
    coordinates are computed on host (data-dependent nnz)."""
    w = weight.data if isinstance(weight, Tensor) else jnp.asarray(weight)
    kd, kh, kw, cin, cout = w.shape
    st, pa, di = _triple(stride), _triple(padding), _triple(dilation)
    idx_np = np.asarray(x._sp.indices, np.int64)       # [nnz, 4]
    spatial = tuple(int(s) for s in x.shape[:4])
    out_sp = tuple(
        (spatial[i + 1] + 2 * pa[i] - di[i] * ((kd, kh, kw)[i] - 1) - 1)
        // st[i] + 1 for i in range(3))

    # host pass: which output coords exist
    out_coords = set()
    for n, d, h, wq in idx_np:
        for od, oh, ow in itertools.product(range(kd), range(kh), range(kw)):
            zs = []
            ok = True
            for i, pos, kk in ((0, d, od), (1, h, oh), (2, wq, ow)):
                num = pos + pa[i] - kk * di[i]
                if num < 0 or num % st[i] or num // st[i] >= out_sp[i]:
                    ok = False
                    break
                zs.append(num // st[i])
            if ok:
                out_coords.add((int(n), zs[0], zs[1], zs[2]))
    if not out_coords:
        raise ValueError("sparse conv produced no output sites")
    out_idx = jnp.asarray(sorted(out_coords), jnp.int32)

    # traced pass: for each output site, gather contributing inputs.
    # out[o] = sum_k W[k] @ x[coord(o)*stride - pad + k*dil]
    grid = _coord_grid(jnp.asarray(x._sp.indices, jnp.int32), spatial)
    vals = x._sp.data
    out = jnp.zeros((out_idx.shape[0], cout), w.dtype)
    stv = jnp.asarray((1,) + st, jnp.int32)
    pav = jnp.asarray((0,) + pa, jnp.int32)
    base = out_idx * stv - pav
    for od, oh, ow in itertools.product(range(kd), range(kh), range(kw)):
        offset = (od * di[0], oh * di[1], ow * di[2])
        nbr_vals = _gather_neighbors(vals, base, grid, offset, spatial)
        out = out + nbr_vals.astype(w.dtype) @ w[od, oh, ow]
    if bias is not None:
        b = bias.data if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + b
    n_dim = (x.shape[0],)
    return SparseCooTensor(
        jsparse.BCOO((out, out_idx), shape=n_dim + out_sp + (cout,)))


class SubmConv3D(Layer):
    """reference python/paddle/sparse/nn/layer/conv.py SubmConv3D
    (NDHWC in, DHWCO weight)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        k = _triple(kernel_size)
        self.weight = self.create_parameter(
            k + (in_channels, out_channels), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False else
                     self.create_parameter((out_channels,), attr=bias_attr,
                                           is_bias=True))
        self._stride, self._padding, self._dilation = stride, padding, dilation

    def forward(self, x):
        return subm_conv3d(x, self.weight, self.bias, self._stride,
                           self._padding, self._dilation)


class Conv3D(SubmConv3D):
    def forward(self, x):
        return conv3d(x, self.weight, self.bias, self._stride,
                      self._padding, self._dilation)


class ReLU(Layer):
    def forward(self, x):
        from . import relu as _relu
        return _relu(x)


class BatchNorm(Layer):
    """Sparse BatchNorm (reference sparse/nn/layer/norm.py): normalizes
    the nnz value rows over the batch-of-points axis."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        self._eps = epsilon
        self._momentum = momentum
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x: SparseCooTensor):
        vals = x._sp.data
        if self.training:
            mean = vals.mean(axis=0)
            var = vals.var(axis=0)
            m = self._momentum
            self._mean._data = m * self._mean._data + (1 - m) * mean
            self._variance._data = m * self._variance._data + (1 - m) * var
        else:
            mean, var = self._mean._data, self._variance._data
        normed = (vals - mean) * jax.lax.rsqrt(var + self._eps)
        out = normed * self.weight.data + self.bias.data
        return SparseCooTensor(
            jsparse.BCOO((out.astype(vals.dtype), x._sp.indices),
                         shape=x.shape))


def attention(query, key, value, sparse_mask: SparseCsrTensor,
              key_padding_mask=None, attn_mask=None, name=None) -> Tensor:
    """CSR-patterned attention (reference
    python/paddle/sparse/nn/functional/transformer.py attention over
    fused_attention_kernel.cu): softmax((QK^T)/sqrt(d) restricted to the
    CSR pattern) @ V. query/key/value are dense [B, H, T, D];
    sparse_mask is [B*H, T, T] CSR giving the kept positions.

    Only the nnz logits exist in the program: per-edge dot products are
    gathered, normalized by a segment-softmax over the row index, and
    scattered back with a segment-sum — never a [T, T] dense score.
    """
    q = query.data if isinstance(query, Tensor) else jnp.asarray(query)
    k = key.data if isinstance(key, Tensor) else jnp.asarray(key)
    v = value.data if isinstance(value, Tensor) else jnp.asarray(value)
    B, H, T, D = q.shape
    scale = 1.0 / np.sqrt(D)

    def one_head(qh, kh, vh, rows, cc):
        logits = (qh[rows] * kh[cc]).sum(-1) * scale
        # numerically-stable segment softmax over rows
        row_max = jax.ops.segment_max(logits, rows, num_segments=T)
        row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
        ex = jnp.exp(logits - row_max[rows])
        denom = jax.ops.segment_sum(ex, rows, num_segments=T)
        p = ex / jnp.maximum(denom[rows], 1e-20)
        return jax.ops.segment_sum(p[:, None] * vh[cc], rows, num_segments=T)

    # The sparsity pattern is static metadata (same stance as the conv
    # coordinate pass): expand CSR row pointers to COO row ids on host.
    indptr = np.asarray(sparse_mask._sp.indptr)       # [B*H, T+1] or [T+1]
    cols_all = np.asarray(sparse_mask._sp.indices)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    if indptr.ndim == 1:                              # shared pattern
        rows = jnp.asarray(np.repeat(np.arange(T), np.diff(indptr)),
                           jnp.int32)
        cc = jnp.asarray(cols_all.ravel(), jnp.int32)
        out = jax.vmap(lambda qh, kh, vh: one_head(qh, kh, vh, rows, cc))(
            qf, kf, vf)
    else:
        indptr = indptr.reshape(B * H, T + 1)
        cols2d = cols_all.reshape(B * H, -1)
        row_tbl = np.stack([np.repeat(np.arange(T), np.diff(indptr[i]))
                            for i in range(B * H)]
                           ) if (indptr[:, -1] == indptr[0, -1]).all() else None
        if row_tbl is not None:
            # uniform nnz across heads: one vmapped kernel, per-head
            # (rows, cols) as batched inputs — no B*H graph unroll
            out = jax.vmap(one_head)(
                qf, kf, vf, jnp.asarray(row_tbl, jnp.int32),
                jnp.asarray(cols2d[:, :indptr[0, -1]], jnp.int32))
        else:                                         # genuinely ragged
            heads = []
            for i in range(B * H):
                # a head's real edges are the first indptr[i, -1] of its
                # (shared-nse padded) slice
                c_i = cols2d[i][:indptr[i, -1]]
                rows = jnp.asarray(
                    np.repeat(np.arange(T), np.diff(indptr[i])), jnp.int32)
                heads.append(one_head(qf[i], kf[i], vf[i], rows,
                                      jnp.asarray(c_i, jnp.int32)))
            out = jnp.stack(heads)
    return Tensor(out.reshape(B, H, T, D))


class functional:  # namespace shim: paddle.sparse.nn.functional
    attention = staticmethod(attention)
    subm_conv3d = staticmethod(subm_conv3d)
    conv3d = staticmethod(conv3d)

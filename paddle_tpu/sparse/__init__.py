"""paddle.sparse namespace.

Reference: python/paddle/sparse/ (COO/CSR tensors + unary/binary/matmul/nn
ops over paddle/phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse BCOO/BCSR — XLA lowers
sparse ops to gather/scatter/segment-sum programs. The TPU MXU has no
sparse units, so genuinely-sparse compute is only a win at high sparsity;
to_dense() is always available to fall back onto the dense path.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor


class SparseCooTensor(Tensor):
    """Tensor subclass carrying a BCOO; dense ops see .data densified
    lazily only when an op needs it."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._sp = bcoo
        super().__init__(jnp.zeros((), jnp.float32))  # placeholder
        self._data = None  # densified on demand

    @property
    def data(self):
        if self._data is None:
            self._data = self._sp.todense()
        return self._data

    @data.setter
    def data(self, v):
        self._data = v

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return True

    @property
    def shape(self):
        return tuple(self._sp.shape)

    def indices(self) -> Tensor:
        return Tensor(self._sp.indices.T)

    def values(self) -> Tensor:
        return Tensor(self._sp.data)

    def nnz(self) -> int:
        return int(self._sp.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._sp.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._sp))


class SparseCsrTensor(Tensor):
    def __init__(self, bcsr):
        self._sp = bcsr
        super().__init__(jnp.zeros((), jnp.float32))
        self._data = None

    @property
    def data(self):
        if self._data is None:
            self._data = self._sp.todense()
        return self._data

    @data.setter
    def data(self, v):
        self._data = v

    def is_sparse(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return True

    @property
    def shape(self):
        return tuple(self._sp.shape)

    def crows(self) -> Tensor:
        return Tensor(self._sp.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._sp.indices)

    def values(self) -> Tensor:
        return Tensor(self._sp.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._sp.todense())


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True):
    """indices [ndim, nnz] + values [nnz] -> COO (python/paddle/sparse/
    creation.py)."""
    idx = jnp.asarray(indices.data if isinstance(indices, Tensor)
                      else indices, jnp.int32).T      # BCOO wants [nnz, ndim]
    vals = jnp.asarray(values.data if isinstance(values, Tensor) else values)
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows = jnp.asarray(crows.data if isinstance(crows, Tensor) else crows,
                        jnp.int32)
    cols = jnp.asarray(cols.data if isinstance(cols, Tensor) else cols,
                       jnp.int32)
    vals = jnp.asarray(values.data if isinstance(values, Tensor) else values)
    return SparseCsrTensor(
        jsparse.BCSR((vals, cols, crows), shape=tuple(shape)))


def _sp(x):
    return x._sp if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x


def matmul(x, y, name=None) -> Tensor:
    """sparse @ dense (phi sparse matmul kernels)."""
    a = _sp(x)
    b = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(a @ b)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        merged = jsparse.BCOO(
            (jnp.concatenate([x._sp.data, y._sp.data]),
             jnp.concatenate([x._sp.indices, y._sp.indices])),
            shape=x._sp.shape).sum_duplicates(nse=x._sp.nse + y._sp.nse)
        return SparseCooTensor(merged)
    return Tensor(x.to_dense().data + y.to_dense().data)


def relu(x, name=None) -> SparseCooTensor:
    sp = _sp(x)
    return SparseCooTensor(jsparse.BCOO((jax.nn.relu(sp.data), sp.indices),
                                        shape=sp.shape))


def sqrt(x, name=None) -> SparseCooTensor:
    sp = _sp(x)
    return SparseCooTensor(jsparse.BCOO((jnp.sqrt(sp.data), sp.indices),
                                        shape=sp.shape))


def sin(x, name=None) -> SparseCooTensor:
    sp = _sp(x)
    return SparseCooTensor(jsparse.BCOO((jnp.sin(sp.data), sp.indices),
                                        shape=sp.shape))


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


from . import nn  # noqa: E402,F401  (after class defs: nn imports them)

"""paddle.sparse namespace.

Reference: python/paddle/sparse/ (COO/CSR tensors + unary/binary/matmul/nn
ops over paddle/phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse BCOO/BCSR — XLA lowers
sparse ops to gather/scatter/segment-sum programs. The TPU MXU has no
sparse units, so genuinely-sparse compute is only a win at high sparsity;
to_dense() is always available to fall back onto the dense path.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor


class SparseCooTensor(Tensor):
    """Tensor subclass carrying a BCOO; dense ops see .data densified
    lazily only when an op needs it."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._sp = bcoo
        super().__init__(jnp.zeros((), jnp.float32))  # placeholder
        self._data = None  # densified on demand

    @property
    def data(self):
        if self._data is None:
            self._data = self._sp.todense()
        return self._data

    @data.setter
    def data(self, v):
        self._data = v

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return True

    @property
    def shape(self):
        return tuple(self._sp.shape)

    def indices(self) -> Tensor:
        return Tensor(self._sp.indices.T)

    def values(self) -> Tensor:
        return Tensor(self._sp.data)

    def nnz(self) -> int:
        return int(self._sp.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._sp.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._sp))


class SparseCsrTensor(Tensor):
    def __init__(self, bcsr):
        self._sp = bcsr
        super().__init__(jnp.zeros((), jnp.float32))
        self._data = None

    @property
    def data(self):
        if self._data is None:
            self._data = self._sp.todense()
        return self._data

    @data.setter
    def data(self, v):
        self._data = v

    def is_sparse(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return True

    @property
    def shape(self):
        return tuple(self._sp.shape)

    def crows(self) -> Tensor:
        return Tensor(self._sp.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._sp.indices)

    def values(self) -> Tensor:
        return Tensor(self._sp.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._sp.todense())


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True):
    """indices [ndim, nnz] + values [nnz] -> COO (python/paddle/sparse/
    creation.py)."""
    idx = jnp.asarray(indices.data if isinstance(indices, Tensor)
                      else indices, jnp.int32).T      # BCOO wants [nnz, ndim]
    vals = jnp.asarray(values.data if isinstance(values, Tensor) else values)
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows = jnp.asarray(crows.data if isinstance(crows, Tensor) else crows,
                        jnp.int32)
    cols = jnp.asarray(cols.data if isinstance(cols, Tensor) else cols,
                       jnp.int32)
    vals = jnp.asarray(values.data if isinstance(values, Tensor) else values)
    return SparseCsrTensor(
        jsparse.BCSR((vals, cols, crows), shape=tuple(shape)))


def _sp(x):
    return x._sp if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x


def matmul(x, y, name=None) -> Tensor:
    """sparse @ dense (phi sparse matmul kernels)."""
    a = _sp(x)
    b = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(a @ b)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        merged = jsparse.BCOO(
            (jnp.concatenate([x._sp.data, y._sp.data]),
             jnp.concatenate([x._sp.indices, y._sp.indices])),
            shape=x._sp.shape).sum_duplicates(nse=x._sp.nse + y._sp.nse)
        return SparseCooTensor(merged)
    return Tensor(x.to_dense().data + y.to_dense().data)


def _unary(fn, name):
    """Zero-preserving elementwise op applied to the stored values only
    (reference python/paddle/sparse/unary.py over phi sparse kernels)."""
    def op(x, *args, **kwargs):
        kwargs.pop("name", None)
        sp = _sp(x)
        vals = fn(sp.data, *args, **kwargs)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(
                jsparse.BCSR((vals, sp.indices, sp.indptr), shape=sp.shape))
        return SparseCooTensor(jsparse.BCOO((vals, sp.indices),
                                            shape=sp.shape))
    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(lambda v: jnp.clip(v, 0, 6), "relu6")
leaky_relu = _unary(
    lambda v, negative_slope=0.01: jnp.where(v > 0, v, negative_slope * v),
    "leaky_relu")
abs = _unary(jnp.abs, "abs")  # noqa: A001 (reference name)
sqrt = _unary(jnp.sqrt, "sqrt")
square = _unary(jnp.square, "square")
sin = _unary(jnp.sin, "sin")
sinh = _unary(jnp.sinh, "sinh")
asin = _unary(jnp.arcsin, "asin")
asinh = _unary(jnp.arcsinh, "asinh")
tan = _unary(jnp.tan, "tan")
tanh = _unary(jnp.tanh, "tanh")
atan = _unary(jnp.arctan, "atan")
atanh = _unary(jnp.arctanh, "atanh")
expm1 = _unary(jnp.expm1, "expm1")
log1p = _unary(jnp.log1p, "log1p")
neg = _unary(jnp.negative, "neg")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
isnan = _unary(jnp.isnan, "isnan")
pow = _unary(jnp.power, "pow")  # noqa: A001


def cast(x, index_dtype=None, value_dtype=None, name=None):
    sp = _sp(x)
    vals = sp.data if value_dtype is None else sp.data.astype(value_dtype)
    idx = sp.indices if index_dtype is None else sp.indices.astype(
        index_dtype)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(
            jsparse.BCSR((vals, idx, sp.indptr), shape=sp.shape))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=sp.shape))


def coalesce(x, name=None) -> "SparseCooTensor":
    sp = _sp(x)
    return SparseCooTensor(sp.sum_duplicates(nse=sp.nse))


def subtract(x, y, name=None):
    # neg() handles both COO and CSR; add() densifies mixed formats
    return add(x, neg(y))


def multiply(x, y, name=None) -> Tensor:
    return Tensor(x.to_dense().data * y.to_dense().data)


def divide(x, y, name=None) -> Tensor:
    return Tensor(x.to_dense().data / y.to_dense().data)


def mv(x, vec, name=None) -> Tensor:
    v = vec.data if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(_sp(x) @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None) -> Tensor:
    """beta*input + alpha*(x @ y), x sparse (reference sparse/multiary.py)."""
    inp = input.data if isinstance(input, Tensor) else jnp.asarray(input)
    yv = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(beta * inp + alpha * (_sp(x) @ yv))


def masked_matmul(x, y, mask, name=None) -> "SparseCsrTensor":
    """Dense @ dense evaluated only at mask's nonzero pattern (reference
    sparse SDDMM, phi/kernels/sparse/gpu/matmul_kernel.cu)."""
    xv = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    sp = _sp(mask)
    dense = xv @ yv
    if isinstance(mask, SparseCsrTensor):
        rows = jnp.asarray(
            np.repeat(np.arange(sp.shape[0]),
                      np.diff(np.asarray(sp.indptr))), jnp.int32)
        vals = dense[rows, jnp.asarray(sp.indices)]
        return SparseCsrTensor(
            jsparse.BCSR((vals, sp.indices, sp.indptr), shape=sp.shape))
    idx = sp.indices
    vals = dense[tuple(idx[:, i] for i in range(idx.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=sp.shape))


def mask_as(x, mask, name=None):
    """Take dense x's values at mask's sparsity pattern."""
    xv = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    sp = _sp(mask)
    idx = sp.indices
    vals = xv[tuple(idx[:, i] for i in range(idx.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=sp.shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    out = jnp.sum(x.to_dense().data, axis=axis, keepdims=keepdim)
    if dtype is not None:
        out = out.astype(dtype)
    return Tensor(out)


def reshape(x, shape, name=None) -> "SparseCooTensor":
    sp = _sp(x)
    return SparseCooTensor(sp.reshape(tuple(shape)))


def transpose(x, perm, name=None) -> "SparseCooTensor":
    sp = _sp(x)
    idx = sp.indices[:, jnp.asarray(perm)]
    new_shape = tuple(sp.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((sp.data, idx), shape=new_shape))


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    import builtins
    dense = x.to_dense().data
    idx = [builtins.slice(None)] * dense.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = builtins.slice(s, e)
    return SparseCooTensor(jsparse.BCOO.fromdense(dense[tuple(idx)]))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from ..ops.linalg import pca_lowrank as _dense_pca
    return _dense_pca(x.to_dense() if hasattr(x, "to_dense") else x,
                      q=q, center=center, niter=niter)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


from . import nn  # noqa: E402,F401  (after class defs: nn imports them)

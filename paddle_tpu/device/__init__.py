"""paddle.device namespace (reference: python/paddle/device/ — set_device,
get_device, cuda.* memory stats).

Device memory on TPU is XLA-managed; per-device HBM numbers come from
jax's memory_stats(). Host staging memory is the native allocator's
(core/allocator.py).
"""
from __future__ import annotations

import jax

from ..framework import (  # noqa: F401
    get_device, set_device, get_default_device, device_count,
    is_compiled_with_tpu,
)
from ..core.allocator import (  # noqa: F401
    memory_stats as host_memory_stats,
    max_memory_allocated as host_max_memory_allocated,
)


def memory_stats(device_id: int = 0) -> dict:
    """Device HBM stats from the XLA backend (empty dict on backends that
    don't report)."""
    d = jax.devices()[device_id]
    return dict(d.memory_stats() or {}) if hasattr(d, "memory_stats") else {}


def max_memory_allocated(device_id: int = 0) -> int:
    return int(memory_stats(device_id).get("peak_bytes_in_use", 0))


def memory_allocated(device_id: int = 0) -> int:
    return int(memory_stats(device_id).get("bytes_in_use", 0))


def max_memory_reserved(device_id: int = 0) -> int:
    return int(memory_stats(device_id).get("bytes_limit", 0))


def synchronize(device_id=None) -> None:
    """Block until pending device work finishes (paddle.device.synchronize).
    XLA's async dispatch drains via a tiny blocking transfer."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()  # noqa: PT002 — this IS the synchronize() API


class cuda:
    """Compat shim: paddle.device.cuda.* maps to the TPU device stats."""
    memory_stats = staticmethod(memory_stats)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    synchronize = staticmethod(synchronize)

    @staticmethod
    def device_count() -> int:
        return device_count()


from .custom import (  # noqa: E402,F401
    register_custom_device, register_custom_devices_from_env,
    get_all_custom_device_type, is_custom_device_available,
)

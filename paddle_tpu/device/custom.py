"""Out-of-tree device plugins — the PJRT answer to CustomDevice.

Reference: paddle/phi/backends/custom/ — a C function-pointer table
(device_ext.h:107-383: ~60 slots covering init/deinit, stream/event,
memcpy h2d/d2h/d2d, allocate, collectives via XCCL) that a vendor .so
fills in, discovered from CUSTOM_DEVICE_ROOT and registered by
``custom_device.cc`` LoadCustomRuntimeLib.

The TPU-native equivalent is the PJRT plugin ABI: the C API every XLA
backend (TPU, GPU, and out-of-tree silicon) implements. One plugin .so
exports ``GetPjrtApi``; JAX discovers it either from the
``PJRT_NAMES_AND_LIBRARY_PATHS`` env (name:path pairs) or from
installed ``jax_plugins.*`` namespace packages. PJRT subsumes both
halves of the reference's ABI — the device table (compile/execute/
transfer/alloc) AND the XCCL collective table (collectives live behind
PJRT's compiled-executable interface) — so this module is deliberately
a registrar, not a reimplementation of a 60-slot table: the stable ABI
already exists, we point the runtime at vendor libraries that speak it.

``register_custom_device("mychip", "/opt/mychip/pjrt_mychip.so")`` is
the CUSTOM_DEVICE_ROOT moment: after it, ``jax.devices("mychip")``
(and therefore every paddle_tpu op, shard_map, collective, and jit) run
on the plugin's devices with no further framework changes.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

_REGISTERED: Dict[str, str] = {}


def register_custom_device(name: str, library_path: str,
                           options: Optional[dict] = None,
                           priority: int = 400) -> None:
    """Register a PJRT plugin .so as backend ``name``.

    Must be called before the first jax operation (backends initialize
    once per process — same constraint as the reference's
    LoadCustomRuntimeLib, which runs at framework-init).
    """
    if not os.path.exists(library_path):
        raise FileNotFoundError(
            f"PJRT plugin for device '{name}' not found: {library_path}")
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        raise RuntimeError(
            "register_custom_device must run before JAX backends "
            "initialize (import paddle_tpu, register, then compute)")
    xla_bridge.register_plugin(
        name, library_path=library_path, options=options, priority=priority)
    _REGISTERED[name] = library_path


def register_custom_devices_from_env(env: str = "PADDLE_TPU_CUSTOM_DEVICES"
                                     ) -> List[str]:
    """Bulk registration from ``name:/path/to/plugin.so;name2:/p2.so``
    (the CUSTOM_DEVICE_ROOT discovery pattern, env-driven)."""
    spec = os.environ.get(env, "")
    names = []
    for pair in filter(None, spec.split(";")):
        name, _, path = pair.partition(":")
        register_custom_device(name.strip(), path.strip())
        names.append(name.strip())
    return names


def get_all_custom_device_type() -> List[str]:
    """Names registered in this process (reference
    python/paddle/device/__init__.py get_all_custom_device_type)."""
    return sorted(_REGISTERED)


def is_custom_device_available(name: str) -> bool:
    if name not in _REGISTERED:
        return False
    import jax
    try:
        return len(jax.devices(name)) > 0
    except RuntimeError:
        return False

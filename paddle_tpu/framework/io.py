"""paddle.save / paddle.load.

Reference: python/paddle/framework/io.py:773 (save), :1020 (load) — nested
state_dict pickled with tensors converted through numpy. Same wire idea
here (numpy + pickle), so checkpoints survive process/device changes;
arrays restore to the default device and can be resharded afterwards
(distributed/checkpoint.py handles the sharded multi-file format).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor, Parameter


_SENTINEL = "_paddle_tpu_tensor_"


def _pack(obj: Any):
    if isinstance(obj, (Tensor, Parameter)):
        return {_SENTINEL: True, "data": np.asarray(obj.data),
                "stop_gradient": obj.stop_gradient,
                "is_param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any, return_numpy: bool):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_param") else Tensor
            t = cls(obj["data"])
            t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol: int = 4, **configs):
    if hasattr(path, "write"):  # file-like
        pickle.dump(_pack(obj), path, protocol=protocol)
        return
    d = os.path.dirname(str(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = bool(configs.get("return_numpy", False))
    if hasattr(path, "read"):
        return _unpack(pickle.load(path), return_numpy)
    with open(path, "rb") as f:
        return _unpack(pickle.load(f), return_numpy)

"""Device/runtime plumbing (reference: python/paddle/device/,
python/paddle/framework/). On TPU, device management is jax's: one process
sees its local TPU chips; placement is explicit via device_put/shardings."""
from __future__ import annotations

import jax

from ..core.flags import get_flag

_current_device = None


def _auto_device():
    devs = jax.devices()
    pref = get_flag("default_device")
    if pref:
        for d in devs:
            if d.platform == pref:
                return d
    return devs[0]


def get_default_device():
    global _current_device
    if _current_device is None:
        _current_device = _auto_device()
    return _current_device


def set_device(device: str):
    """paddle.device.set_device — accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0'."""
    global _current_device
    name = device.lower()
    plat, _, idx = name.partition(":")
    plat = {"gpu": "cuda", "xpu": "tpu"}.get(plat, plat)
    idx = int(idx) if idx else 0
    cands = [d for d in jax.devices() if d.platform == plat] or \
            ([d for d in jax.local_devices(backend="cpu")] if plat == "cpu" else [])
    if not cands:
        # tolerate 'tpu' requests on CPU-only test rigs: fall back
        cands = jax.devices()
    _current_device = cands[min(idx, len(cands) - 1)]
    return _current_device


def get_device() -> str:
    d = get_default_device()
    return f"{d.platform}:{getattr(d, 'id', 0)}"


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role; report True for API parity of capability checks
    return True


class _Place:
    """Device placement token (reference paddle.CPUPlace/CUDAPlace/
    XPUPlace, paddle/phi/common/place.h). On this build placement is
    XLA's job; Places resolve to jax devices for `paddle.device` calls
    and to_tensor(place=...)."""

    _platform = "cpu"

    def __init__(self, device_id: int = 0):
        self._id = device_id

    def get_device_id(self) -> int:
        return self._id

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == self._platform]
        if not devs:  # fall back to default (e.g. CUDAPlace on a TPU host)
            devs = jax.devices()
        return devs[min(self._id, len(devs) - 1)]

    def __repr__(self):
        return f"{type(self).__name__}({self._id})"

    def __eq__(self, other):
        return type(self) is type(other) and self._id == other._id

    def __hash__(self):
        return hash((type(self).__name__, self._id))


class CPUPlace(_Place):
    _platform = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace(_Place):
    # accepted for API compat; resolves to the accelerator (TPU) device
    _platform = "tpu"


class CUDAPinnedPlace(CPUPlace):
    pass


class TPUPlace(_Place):
    _platform = "tpu"

"""Device/runtime plumbing (reference: python/paddle/device/,
python/paddle/framework/). On TPU, device management is jax's: one process
sees its local TPU chips; placement is explicit via device_put/shardings."""
from __future__ import annotations

import jax

from ..core.flags import get_flag

_current_device = None


def _auto_device():
    devs = jax.devices()
    pref = get_flag("default_device")
    if pref:
        for d in devs:
            if d.platform == pref:
                return d
    return devs[0]


def get_default_device():
    global _current_device
    if _current_device is None:
        _current_device = _auto_device()
    return _current_device


def set_device(device: str):
    """paddle.device.set_device — accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0'."""
    global _current_device
    name = device.lower()
    plat, _, idx = name.partition(":")
    plat = {"gpu": "cuda", "xpu": "tpu"}.get(plat, plat)
    idx = int(idx) if idx else 0
    cands = [d for d in jax.devices() if d.platform == plat] or \
            ([d for d in jax.local_devices(backend="cpu")] if plat == "cpu" else [])
    if not cands:
        # tolerate 'tpu' requests on CPU-only test rigs: fall back
        cands = jax.devices()
    _current_device = cands[min(idx, len(cands) - 1)]
    return _current_device


def get_device() -> str:
    d = get_default_device()
    return f"{d.platform}:{getattr(d, 'id', 0)}"


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role; report True for API parity of capability checks
    return True

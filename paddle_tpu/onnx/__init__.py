"""paddle.onnx namespace (reference: python/paddle/onnx/export.py via
paddle2onnx). This build emits ONNX ModelProto directly in protobuf
wire format (export.py) for Sequential-style models — Linear/Conv/BN/
activation/pool chains, which covers the vision zoo — and falls back to
the StableHLO artifact (jit.save) with a warning for graphs beyond that
subset.
"""
from __future__ import annotations

from .export import export  # noqa: F401

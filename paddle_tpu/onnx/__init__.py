"""paddle.onnx namespace (reference: python/paddle/onnx/export.py via
paddle2onnx). In this framework the portable deployment artifact is
StableHLO (jit.save), which ONNX runtimes do not consume; export() saves
the StableHLO artifact and says so rather than silently produce nothing.
"""
from __future__ import annotations


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    from .. import jit

    jit.save(layer, path, input_spec=input_spec)
    import warnings
    warnings.warn(
        "paddle_tpu has no paddle2onnx; exported StableHLO to "
        f"{path}.pdmodel instead (load with paddle_tpu.inference or "
        "jit.load)")
    return path + ".pdmodel"

"""Real ONNX export for layer chains.

Reference: python/paddle/onnx/export.py (delegates to paddle2onnx — a
full Program->ONNX compiler). This build has no onnx package, so the
exporter emits ModelProto in protobuf wire format directly (_proto.py)
for the layer types that cover the vision zoo and MLP-style models:
Linear, Conv2D, BatchNorm1D/2D, ReLU/ReLU6/Sigmoid/Tanh/Softmax/GELU/
LeakyReLU/Hardswish/Hardsigmoid, MaxPool2D, AvgPool2D,
AdaptiveAvgPool2D (global), Flatten, Dropout (eval identity),
PixelShuffle-free Sequential composition.

The graph is recorded on a tracing run as a DAG of events: forward
hooks capture leaf-layer calls, and the op registry's trace hook
captures the FUNCTIONAL glue between them (residual adds, flatten(1),
scalar scaling) — so branchy graphs like ResNet's residual blocks
export as real ONNX, not just linear Sequential chains. Graphs using
ops with no ONNX mapping fall back to jit.save (StableHLO) with a
warning.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from . import _proto as P

# onnx.proto field numbers (public spec)
_IR_VERSION = 8
_OPSET = 13

# TensorProto.DataType
_F32 = 1
_I64 = 7


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = _F32 if arr.dtype != np.int64 else _I64
    if dt == _F32:
        arr = arr.astype(np.float32)
    msg = b"".join([
        *(P.field_varint(1, int(d)) for d in arr.shape),   # dims
        P.field_varint(2, dt),                             # data_type
        P.field_string(8, name),                           # name
        P.field_bytes(9, arr.tobytes()),                   # raw_data
    ])
    return msg


def _value_info(name: str, shape, elem=_F32) -> bytes:
    dims = b"".join(
        P.field_message(1, P.field_varint(1, int(d)) if d is not None
                        else P.field_string(2, "N"))
        for d in shape)
    tensor_type = (P.field_varint(1, elem)
                   + P.field_message(2, dims))              # shape
    type_proto = P.field_message(1, tensor_type)            # tensor_type
    return P.field_string(1, name) + P.field_message(2, type_proto)


def _attr_int(name: str, v: int) -> bytes:
    return (P.field_string(1, name) + P.field_varint(3, v)
            + P.field_varint(20, 2))                        # type=INT


def _attr_ints(name: str, vs) -> bytes:
    return (P.field_string(1, name)
            + b"".join(P.field_varint(8, int(v)) for v in vs)
            + P.field_varint(20, 7))                        # type=INTS


def _attr_string(name: str, v: str) -> bytes:
    return (P.field_string(1, name) + P.field_bytes(4, v.encode())
            + P.field_varint(20, 3))                        # type=STRING


def _attr_float(name: str, v: float) -> bytes:
    import struct
    return (P.field_string(1, name)
            + P._varint(2 << 3 | 5) + struct.pack("<f", v)
            + P.field_varint(20, 1))                        # type=FLOAT


def _node(op_type: str, inputs, outputs, attrs: List[bytes] = (),
          name: str = "") -> bytes:
    return b"".join([
        *(P.field_string(1, i) for i in inputs),
        *(P.field_string(2, o) for o in outputs),
        P.field_string(3, name or outputs[0]),
        P.field_string(4, op_type),
        *(P.field_message(5, a) for a in attrs),
    ])


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


_OP_MIN_OPSET = {"Gelu": 20, "HardSwish": 14}


def _onnx_pads(pa):
    """paddle padding spec -> onnx pads (h0, w0, h1, w1); None when the
    spec (string SAME/VALID) has no static equivalent."""
    if isinstance(pa, str):
        return None
    if isinstance(pa, (tuple, list)) and len(pa) == 4:
        # paddle [h_lo, h_hi, w_lo, w_hi] -> onnx [h0, w0, h1, w1]
        return (pa[0], pa[2], pa[1], pa[3])
    if isinstance(pa, (tuple, list)) and len(pa) == 2 and \
            isinstance(pa[0], (tuple, list)):
        return (pa[0][0], pa[1][0], pa[0][1], pa[1][1])
    ph, pw = _pair(pa)
    return (ph, pw, ph, pw)


class _Emitter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.inits: List[bytes] = []
        self.counter = 0
        self.min_opset = 7

    def tname(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def add_init(self, base, arr):
        name = self.tname(base)
        self.inits.append(_tensor_proto(name, np.asarray(arr)))
        return name

    def emit(self, layer, x_name: str) -> Optional[str]:
        """Emit node(s) for `layer` consuming x_name; returns output
        name, or None if the layer type is unsupported."""
        from .. import nn
        t = type(layer).__name__
        out = self.tname(t.lower())
        if isinstance(layer, nn.Linear):
            w = self.add_init("weight", np.asarray(layer.weight.data))
            ins = [x_name, w]
            if layer.bias is not None:
                ins.append(self.add_init("bias",
                                         np.asarray(layer.bias.data)))
            # our weight layout is [in, out] = Gemm's B untransposed
            self.nodes.append(_node("Gemm", ins, [out]))
            return out
        if isinstance(layer, nn.Conv2D):
            w = self.add_init("weight", np.asarray(layer.weight.data))
            ins = [x_name, w]
            if layer.bias is not None:
                ins.append(self.add_init("bias",
                                         np.asarray(layer.bias.data)))
            st = _pair(layer.stride)
            pads = _onnx_pads(layer.padding)
            if pads is None:
                return None  # SAME/VALID: shape math differs; use jit.save
            di = _pair(layer.dilation)
            attrs = [_attr_ints("strides", st),
                     _attr_ints("pads", pads),
                     _attr_ints("dilations", di),
                     _attr_int("group", layer.groups)]
            self.nodes.append(_node("Conv", ins, [out], attrs))
            return out
        if isinstance(layer, (nn.BatchNorm1D, nn.BatchNorm2D)):
            nf = layer.num_features
            scale = self.add_init(
                "scale", np.asarray(layer.weight.data)
                if layer.weight is not None else np.ones(nf, np.float32))
            bias = self.add_init(
                "b", np.asarray(layer.bias.data)
                if layer.bias is not None else np.zeros(nf, np.float32))
            mean = self.add_init("mean", np.asarray(layer._mean.data))
            var = self.add_init("var", np.asarray(layer._variance.data))
            self.nodes.append(_node(
                "BatchNormalization", [x_name, scale, bias, mean, var],
                [out], [_attr_float("epsilon", float(layer.epsilon))]))
            return out
        simple = {"ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh",
                  "Hardswish": "HardSwish", "Hardsigmoid": "HardSigmoid"}
        if t in simple:
            self.nodes.append(_node(simple[t], [x_name], [out]))
            self.min_opset = max(self.min_opset, _OP_MIN_OPSET.get(
                simple[t], 7))
            return out
        if t == "GELU":
            approx = getattr(layer, "_kwargs", {}).get("approximate", False)
            self.nodes.append(_node(
                "Gelu", [x_name], [out],
                [_attr_string("approximate",
                              "tanh" if approx else "none")]))
            self.min_opset = max(self.min_opset, 20)
            return out
        if t == "Softmax":
            axis = getattr(layer, "_kwargs", {}).get("axis", -1)
            self.nodes.append(_node("Softmax", [x_name], [out],
                                    [_attr_int("axis", int(axis))]))
            self.min_opset = max(self.min_opset, 13)  # axis semantics
            return out
        if t == "Flatten":
            if getattr(layer, "stop_axis", -1) != -1:
                return None  # ONNX Flatten has only a start axis
            self.nodes.append(_node(
                "Flatten", [x_name], [out],
                [_attr_int("axis", int(getattr(layer, "start_axis", 1)))]))
            return out
        if t == "ReLU6":
            self.nodes.append(_node("Clip", [
                x_name, self.add_init("min", np.float32(0.0)),
                self.add_init("max", np.float32(6.0))], [out]))
            self.min_opset = max(self.min_opset, 11)  # min/max as inputs
            return out
        if t == "LeakyReLU":
            alpha = getattr(layer, "_kwargs", {}).get("negative_slope", 0.01)
            self.nodes.append(_node(
                "LeakyRelu", [x_name], [out],
                [_attr_float("alpha", float(alpha))]))
            return out
        if t in ("Dropout", "Dropout2D", "Dropout3D", "Identity"):
            self.nodes.append(_node("Identity", [x_name], [out]))
            return out
        if isinstance(layer, (nn.MaxPool2D, nn.AvgPool2D)):
            pads = _onnx_pads(layer.padding)
            if pads is None:
                return None  # string/SAME padding: use the StableHLO path
            k = _pair(layer.kernel_size)
            st = _pair(layer.stride if layer.stride is not None
                       else layer.kernel_size)
            op = ("MaxPool" if isinstance(layer, nn.MaxPool2D)
                  else "AveragePool")
            self.nodes.append(_node(
                op, [x_name], [out],
                [_attr_ints("kernel_shape", k), _attr_ints("strides", st),
                 _attr_ints("pads", pads)]))
            return out
        if isinstance(layer, nn.AdaptiveAvgPool2D):
            if tuple(np.atleast_1d(layer.output_size)) in ((1,), (1, 1)):
                self.nodes.append(_node("GlobalAveragePool", [x_name],
                                        [out]))
                return out
            return None
        return None

    _ELTWISE = {"add": "Add", "subtract": "Sub", "multiply": "Mul",
                "divide": "Div"}

    def emit_functional(self, opname, args, kwargs, out_t, names,
                        traced_ids):
        """Emit a node for a FUNCTIONAL registry op recorded between
        layer calls (the residual add / flatten(1) glue in forward()
        bodies — what makes branchy graphs like ResNet exportable).
        Returns the output name, or None when unsupported.

        ``traced_ids``: ids of every tensor PRODUCED during the trace.
        A produced-but-unnamed tensor (e.g. an element of a tuple
        output) must abort the export — baking it as a constant would
        freeze a zeros-derived activation into the model. Tensors that
        predate the trace (user constants) are genuine initializers.
        """
        from ..core.tensor import Tensor

        def in_name(v):
            if isinstance(v, Tensor):
                nm = names.get(id(v))
                if nm is not None:
                    return nm
                if id(v) in traced_ids:
                    return None  # un-named intermediate: not exportable
                return self.add_init("const", np.asarray(v.data))
            return self.add_init("const", np.asarray(v, np.float32))

        o = self.tname(opname)
        if opname in self._ELTWISE:
            an, bn = in_name(args[0]), in_name(args[1])
            if an is None or bn is None:
                return None
            self.nodes.append(_node(self._ELTWISE[opname], [an, bn], [o]))
            return o
        if opname == "relu":
            an = in_name(args[0])
            if an is None:
                return None
            self.nodes.append(_node("Relu", [an], [o]))
            return o
        if opname in ("flatten", "reshape"):
            # static re-shape with a dynamic batch: Reshape with 0 in
            # dim 0 (ONNX: copy the input's dim) — only valid when the
            # op PRESERVES dim 0 (flatten(start_axis=0) / reshape([-1])
            # fold the batch in and must fall back)
            src = args[0]
            if not (isinstance(src, Tensor) and src.ndim >= 1
                    and out_t.ndim >= 1
                    and src.shape[0] == out_t.shape[0]):
                return None
            an = in_name(src)
            if an is None:
                return None
            tgt = [0] + [int(d) for d in out_t.shape[1:]]
            shp = self.add_init("shape", np.asarray(tgt, np.int64))
            self.nodes.append(_node("Reshape", [an, shp], [o]))
            return o
        return None


def export(layer, path: str, input_spec=None, opset_version: int = _OPSET,
           **configs) -> str:
    """Export a Layer's traced graph (DAG, residual adds included) to a
    real .onnx file.

    Falls back to jit.save (StableHLO) with a warning when the model
    contains layers or graph shapes the ONNX emitter doesn't cover —
    deployment through inference.Config still works in that case.
    """
    from .. import nn, jit

    if input_spec is None:
        raise ValueError("onnx.export needs input_spec=[InputSpec(shape)] "
                         "to trace the model")
    spec = input_spec[0]
    decl_shape = [d if (d or 0) > 0 else None for d in spec.shape]
    shape = [d if d is not None else 1 for d in decl_shape]

    # Trace to an EVENT list (core/graph_trace.py — shared with the
    # inference passes): one event per supported leaf layer (the
    # structured emitters above), plus one event per FUNCTIONAL registry
    # op executed outside any leaf layer (the residual add, flatten(1),
    # F.relu glue in forward() bodies). Primitive ops fired INSIDE a
    # leaf layer are subsumed by that layer's event.
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core.graph_trace import trace_layer_graph
    x = Tensor(jnp.zeros(tuple(shape), jnp.float32))
    tr = trace_layer_graph(layer, x)
    events, traced_ids, y = tr.events, tr.traced_ids, tr.y

    em = _Emitter()
    out_name = "input"
    obj_to_name = {id(x): "input"}
    supported = bool(events)
    for ev in events:
        if ev[0] == "layer":
            _, l, inputs, output = ev
            src = inputs[0] if isinstance(inputs, tuple) else inputs
            if id(src) not in obj_to_name:
                supported = False  # layer fed by something untraced
                break
            nm = em.emit(l, obj_to_name[id(src)])
            if nm is None:
                supported = False
                break
            obj_to_name[id(output)] = nm
            out_name = nm
        else:
            _, opname, args, kwargs, out = ev
            nm = em.emit_functional(opname, args, kwargs, out,
                                    obj_to_name, traced_ids)
            if nm is None:
                supported = False
                break
            obj_to_name[id(out)] = nm
            out_name = nm
    # the model's return value must BE a traced output, or forward()
    # post-processing would be dropped
    if supported and id(y) in obj_to_name:
        out_name = obj_to_name[id(y)]
    else:
        supported = False
    if not supported or not events:
        import warnings
        jit.save(layer, path, input_spec=input_spec)
        warnings.warn(
            "onnx.export covers DAGs of Linear/Conv/BN/activation/pool "
            "layers plus elementwise/reshape glue; this model uses ops "
            "without an ONNX mapping — exported StableHLO to "
            f"{path}.pdmodel instead (paddle_tpu.inference loads it)")
        return path + ".pdmodel"

    graph = b"".join([
        *(P.field_message(1, n) for n in em.nodes),
        P.field_string(2, type(layer).__name__),
        *(P.field_message(5, t) for t in em.inits),
        P.field_message(11, _value_info("input", decl_shape)),
        P.field_message(12, _value_info(
            out_name, [None if decl_shape[0] is None and i == 0 else int(d)
                       for i, d in enumerate(np.shape(y.data))])),
    ])
    final_opset = max(opset_version, em.min_opset)
    opset = P.field_string(1, "") + P.field_varint(2, final_opset)
    model = b"".join([
        P.field_varint(1, _IR_VERSION),
        P.field_string(2, "paddle_tpu"),
        P.field_string(3, "0.3"),
        P.field_message(7, graph),
        P.field_message(8, opset),
    ])
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path

"""Real ONNX export for layer chains.

Reference: python/paddle/onnx/export.py (delegates to paddle2onnx — a
full Program->ONNX compiler). This build has no onnx package, so the
exporter emits ModelProto in protobuf wire format directly (_proto.py)
for the layer types that cover the vision zoo and MLP-style models:
Linear, Conv2D, BatchNorm1D/2D, ReLU/ReLU6/Sigmoid/Tanh/Softmax/GELU/
LeakyReLU/Hardswish/Hardsigmoid, MaxPool2D, AvgPool2D,
AdaptiveAvgPool2D (global), Flatten, Dropout (eval identity),
PixelShuffle-free Sequential composition.

The graph is recorded on a tracing run as a DAG of events: forward
hooks capture leaf-layer calls, and the op registry's trace hook
captures the FUNCTIONAL glue between them (residual adds, flatten(1),
scalar scaling) — so branchy graphs like ResNet's residual blocks
export as real ONNX, not just linear Sequential chains. Graphs using
ops with no ONNX mapping fall back to jit.save (StableHLO) with a
warning.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from . import _proto as P

# onnx.proto field numbers (public spec)
_IR_VERSION = 8
_OPSET = 13

# TensorProto.DataType
_F32 = 1
_I32 = 6
_I64 = 7


def _elem_type(dtype) -> int:
    dt = np.dtype(dtype)
    if dt == np.int64:
        return _I64
    if dt in (np.int32, np.int16, np.int8, np.uint8):
        return _I32
    return _F32


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    if arr.ndim:  # ascontiguousarray PROMOTES 0-d to 1-d
        arr = np.ascontiguousarray(arr)
    dt = _elem_type(arr.dtype)
    arr = arr.astype({_F32: np.float32, _I32: np.int32,
                      _I64: np.int64}[dt])
    msg = b"".join([
        *(P.field_varint(1, int(d)) for d in arr.shape),   # dims
        P.field_varint(2, dt),                             # data_type
        P.field_string(8, name),                           # name
        P.field_bytes(9, arr.tobytes()),                   # raw_data
    ])
    return msg


def _value_info(name: str, shape, elem=_F32) -> bytes:
    dims = b"".join(
        P.field_message(1, P.field_varint(1, int(d)) if d is not None
                        else P.field_string(2, "N"))
        for d in shape)
    tensor_type = (P.field_varint(1, elem)
                   + P.field_message(2, dims))              # shape
    type_proto = P.field_message(1, tensor_type)            # tensor_type
    return P.field_string(1, name) + P.field_message(2, type_proto)


def _attr_int(name: str, v: int) -> bytes:
    return (P.field_string(1, name) + P.field_varint(3, v)
            + P.field_varint(20, 2))                        # type=INT


def _attr_ints(name: str, vs) -> bytes:
    return (P.field_string(1, name)
            + b"".join(P.field_varint(8, int(v)) for v in vs)
            + P.field_varint(20, 7))                        # type=INTS


def _attr_string(name: str, v: str) -> bytes:
    return (P.field_string(1, name) + P.field_bytes(4, v.encode())
            + P.field_varint(20, 3))                        # type=STRING


def _attr_float(name: str, v: float) -> bytes:
    import struct
    return (P.field_string(1, name)
            + P._varint(2 << 3 | 5) + struct.pack("<f", v)
            + P.field_varint(20, 1))                        # type=FLOAT


def _node(op_type: str, inputs, outputs, attrs: List[bytes] = (),
          name: str = "") -> bytes:
    return b"".join([
        *(P.field_string(1, i) for i in inputs),
        *(P.field_string(2, o) for o in outputs),
        P.field_string(3, name or outputs[0]),
        P.field_string(4, op_type),
        *(P.field_message(5, a) for a in attrs),
    ])


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


_OP_MIN_OPSET = {"Gelu": 20, "HardSwish": 14}


def _onnx_pads(pa):
    """paddle padding spec -> onnx pads (h0, w0, h1, w1); None when the
    spec (string SAME/VALID) has no static equivalent."""
    if isinstance(pa, str):
        return None
    if isinstance(pa, (tuple, list)) and len(pa) == 4:
        # paddle [h_lo, h_hi, w_lo, w_hi] -> onnx [h0, w0, h1, w1]
        return (pa[0], pa[2], pa[1], pa[3])
    if isinstance(pa, (tuple, list)) and len(pa) == 2 and \
            isinstance(pa[0], (tuple, list)):
        return (pa[0][0], pa[1][0], pa[0][1], pa[1][1])
    ph, pw = _pair(pa)
    return (ph, pw, ph, pw)


class _Emitter:
    def __init__(self, names=None, traced_ids=None):
        self.nodes: List[bytes] = []
        self.inits: List[bytes] = []
        self.counter = 0
        self.min_opset = 7
        # id(tensor) -> graph value name, and the set of ids PRODUCED
        # during the trace (an unnamed produced tensor aborts export;
        # a tensor predating the trace is a genuine initializer)
        self.names = names if names is not None else {}
        self.traced_ids = traced_ids if traced_ids is not None else set()

    def in_name(self, v, out_t=None) -> Optional[str]:
        """Graph name for an op input: a traced name, a baked
        initializer for pre-trace constants (dtype-faithful — an int32
        ids tensor must not become a float32 initializer), or None when
        the value is an unnamed traced intermediate."""
        from ..core.tensor import Tensor
        if isinstance(v, Tensor):
            nm = self.names.get(id(v))
            if nm is not None:
                return nm
            if id(v) in self.traced_ids:
                return None
            return self.add_init("const", np.asarray(v.data))
        dt = (np.dtype(str(out_t.dtype).split(".")[-1])
              if out_t is not None and hasattr(out_t, "dtype")
              else np.float32)
        if np.issubdtype(dt, np.integer):
            dt = np.int64 if dt == np.int64 else np.int32
        return self.add_init("const", np.asarray(v, dt))

    def tname(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def add_init(self, base, arr):
        name = self.tname(base)
        self.inits.append(_tensor_proto(name, np.asarray(arr)))
        return name

    def emit(self, layer, x_name: str) -> Optional[str]:
        """Emit node(s) for `layer` consuming x_name; returns output
        name, or None if the layer type is unsupported."""
        from .. import nn
        t = type(layer).__name__
        out = self.tname(t.lower())
        if isinstance(layer, nn.Linear):
            w = self.add_init("weight", np.asarray(layer.weight.data))
            ins = [x_name, w]
            if layer.bias is not None:
                ins.append(self.add_init("bias",
                                         np.asarray(layer.bias.data)))
            # our weight layout is [in, out] = Gemm's B untransposed
            self.nodes.append(_node("Gemm", ins, [out]))
            return out
        if isinstance(layer, nn.Conv2D):
            w = self.add_init("weight", np.asarray(layer.weight.data))
            ins = [x_name, w]
            if layer.bias is not None:
                ins.append(self.add_init("bias",
                                         np.asarray(layer.bias.data)))
            st = _pair(layer.stride)
            pads = _onnx_pads(layer.padding)
            if pads is None:
                return None  # SAME/VALID: shape math differs; use jit.save
            di = _pair(layer.dilation)
            attrs = [_attr_ints("strides", st),
                     _attr_ints("pads", pads),
                     _attr_ints("dilations", di),
                     _attr_int("group", layer.groups)]
            self.nodes.append(_node("Conv", ins, [out], attrs))
            return out
        if isinstance(layer, (nn.BatchNorm1D, nn.BatchNorm2D)):
            nf = layer.num_features
            scale = self.add_init(
                "scale", np.asarray(layer.weight.data)
                if layer.weight is not None else np.ones(nf, np.float32))
            bias = self.add_init(
                "b", np.asarray(layer.bias.data)
                if layer.bias is not None else np.zeros(nf, np.float32))
            mean = self.add_init("mean", np.asarray(layer._mean.data))
            var = self.add_init("var", np.asarray(layer._variance.data))
            self.nodes.append(_node(
                "BatchNormalization", [x_name, scale, bias, mean, var],
                [out], [_attr_float("epsilon", float(layer.epsilon))]))
            return out
        if isinstance(layer, nn.Embedding):
            w = self.add_init("embed", np.asarray(layer.weight.data))
            self.nodes.append(_node("Gather", [w, x_name], [out],
                                    [_attr_int("axis", 0)]))
            return out
        if isinstance(layer, nn.LayerNorm):
            if len(layer.normalized_shape) != 1:
                return None  # multi-axis norm: StableHLO path
            nf = layer.normalized_shape[0]
            scale = self.add_init(
                "scale", np.asarray(layer.weight.data)
                if layer.weight is not None else np.ones(nf, np.float32))
            bias = self.add_init(
                "b", np.asarray(layer.bias.data)
                if layer.bias is not None else np.zeros(nf, np.float32))
            self.nodes.append(_node(
                "LayerNormalization", [x_name, scale, bias], [out],
                [_attr_int("axis", -1),
                 _attr_float("epsilon", float(layer.epsilon))]))
            self.min_opset = max(self.min_opset, 17)
            return out
        simple = {"ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh",
                  "Hardswish": "HardSwish", "Hardsigmoid": "HardSigmoid"}
        if t in simple:
            self.nodes.append(_node(simple[t], [x_name], [out]))
            self.min_opset = max(self.min_opset, _OP_MIN_OPSET.get(
                simple[t], 7))
            return out
        if t == "GELU":
            approx = getattr(layer, "_kwargs", {}).get("approximate", False)
            self.nodes.append(_node(
                "Gelu", [x_name], [out],
                [_attr_string("approximate",
                              "tanh" if approx else "none")]))
            self.min_opset = max(self.min_opset, 20)
            return out
        if t == "Softmax":
            axis = getattr(layer, "_kwargs", {}).get("axis", -1)
            self.nodes.append(_node("Softmax", [x_name], [out],
                                    [_attr_int("axis", int(axis))]))
            self.min_opset = max(self.min_opset, 13)  # axis semantics
            return out
        if t == "Flatten":
            if getattr(layer, "stop_axis", -1) != -1:
                return None  # ONNX Flatten has only a start axis
            self.nodes.append(_node(
                "Flatten", [x_name], [out],
                [_attr_int("axis", int(getattr(layer, "start_axis", 1)))]))
            return out
        if t == "ReLU6":
            self.nodes.append(_node("Clip", [
                x_name, self.add_init("min", np.float32(0.0)),
                self.add_init("max", np.float32(6.0))], [out]))
            self.min_opset = max(self.min_opset, 11)  # min/max as inputs
            return out
        if t == "LeakyReLU":
            alpha = getattr(layer, "_kwargs", {}).get("negative_slope", 0.01)
            self.nodes.append(_node(
                "LeakyRelu", [x_name], [out],
                [_attr_float("alpha", float(alpha))]))
            return out
        if t in ("Dropout", "Dropout2D", "Dropout3D", "Identity"):
            self.nodes.append(_node("Identity", [x_name], [out]))
            return out
        if isinstance(layer, (nn.MaxPool2D, nn.AvgPool2D)):
            pads = _onnx_pads(layer.padding)
            if pads is None:
                return None  # string/SAME padding: use the StableHLO path
            k = _pair(layer.kernel_size)
            st = _pair(layer.stride if layer.stride is not None
                       else layer.kernel_size)
            op = ("MaxPool" if isinstance(layer, nn.MaxPool2D)
                  else "AveragePool")
            self.nodes.append(_node(
                op, [x_name], [out],
                [_attr_ints("kernel_shape", k), _attr_ints("strides", st),
                 _attr_ints("pads", pads)]))
            return out
        if isinstance(layer, nn.AdaptiveAvgPool2D):
            if tuple(np.atleast_1d(layer.output_size)) in ((1,), (1, 1)):
                self.nodes.append(_node("GlobalAveragePool", [x_name],
                                        [out]))
                return out
            return None
        return None

    _ELTWISE = {"add": "Add", "subtract": "Sub", "multiply": "Mul",
                "divide": "Div"}

    def _n(self, op_type, inputs, base, attrs=()):
        o = self.tname(base)
        self.nodes.append(_node(op_type, inputs, [o], list(attrs)))
        return o

    def emit_functional(self, opname, args, kwargs, out_t):
        """Emit node(s) for a FUNCTIONAL registry op recorded between
        layer calls — the residual add / flatten(1) glue plus the
        transformer set (matmul, softmax, transpose, reshape, gelu/erf,
        getitem, scaled_dot_product_attention) that makes the in-repo
        ERNIE encoder export as real ONNX. Returns the output name, or
        None when unsupported (the caller falls back to StableHLO).

        Unnamed traced intermediates (see in_name) abort the export —
        baking them would freeze a zeros-derived activation into the
        model. Tensors predating the trace are genuine initializers.
        """
        from ..core.tensor import Tensor

        in_name = lambda v: self.in_name(v, out_t)
        o = self.tname(opname)
        if opname in self._ELTWISE:
            an, bn = in_name(args[0]), in_name(args[1])
            if an is None or bn is None:
                return None
            self.nodes.append(_node(self._ELTWISE[opname], [an, bn], [o]))
            return o
        if opname == "relu":
            an = in_name(args[0])
            if an is None:
                return None
            self.nodes.append(_node("Relu", [an], [o]))
            return o
        if opname == "erf":
            an = in_name(args[0])
            if an is None:
                return None
            self.nodes.append(_node("Erf", [an], [o]))
            self.min_opset = max(self.min_opset, 9)
            return o
        if opname == "matmul":
            if kwargs.get("transpose_x") or kwargs.get("transpose_y"):
                return None
            an, bn = in_name(args[0]), in_name(args[1])
            if an is None or bn is None:
                return None
            self.nodes.append(_node("MatMul", [an, bn], [o]))
            return o
        if opname == "softmax":
            an = in_name(args[0])
            if an is None:
                return None
            axis = kwargs.get("axis", args[1] if len(args) > 1 else -1)
            self.nodes.append(_node("Softmax", [an], [o],
                                    [_attr_int("axis", int(axis))]))
            self.min_opset = max(self.min_opset, 13)
            return o
        if opname == "transpose":
            perm = kwargs.get("perm", args[1] if len(args) > 1 else None)
            an = in_name(args[0])
            if an is None or perm is None:
                return None
            self.nodes.append(_node(
                "Transpose", [an], [o],
                [_attr_ints("perm", [int(p) for p in perm])]))
            return o
        if opname == "gelu":
            an = in_name(args[0])
            if an is None:
                return None
            approx = kwargs.get("approximate",
                                args[1] if len(args) > 1 else False)
            if approx:
                # 0.5 x (1 + tanh(sqrt(2/pi) (x + 0.044715 x^3)))
                c = lambda v: self.add_init("c", np.float32(v))
                x3 = self._n("Mul", [an, self._n(
                    "Mul", [an, an], "x2")], "x3")
                inner = self._n("Add", [an, self._n(
                    "Mul", [x3, c(0.044715)], "sx3")], "inner")
                th = self._n("Tanh", [self._n(
                    "Mul", [inner, c(float(np.sqrt(2.0 / np.pi)))],
                    "si")], "th")
                one = self._n("Add", [th, c(1.0)], "one_p")
                half = self._n("Mul", [an, c(0.5)], "halfx")
                self.nodes.append(_node("Mul", [half, one], [o]))
                return o
            # exact: 0.5 x (1 + erf(x / sqrt(2)))
            c = lambda v: self.add_init("c", np.float32(v))
            er = self._n("Erf", [self._n(
                "Div", [an, c(float(np.sqrt(2.0)))], "xs")], "erf")
            one = self._n("Add", [er, c(1.0)], "one_p")
            half = self._n("Mul", [an, c(0.5)], "halfx")
            self.nodes.append(_node("Mul", [half, one], [o]))
            self.min_opset = max(self.min_opset, 9)
            return o
        if opname == "getitem":
            # single integer index on one axis (seq[:, 0] pooling):
            # Gather with a scalar index drops that axis, like numpy
            src = args[0]
            key = kwargs.get("key", args[1] if len(args) > 1 else None)
            if not isinstance(src, Tensor) or key is None:
                return None
            key = key if isinstance(key, tuple) else (key,)
            ints = [(i, k) for i, k in enumerate(key)
                    if isinstance(k, int)]
            full = all(isinstance(k, int)
                       or (isinstance(k, slice)
                           and k == slice(None, None, None))
                       for k in key)
            if len(ints) != 1 or not full:
                return None
            an = in_name(src)
            if an is None:
                return None
            axis, idx = ints[0]
            gi = self.add_init("idx", np.asarray(idx, np.int64))
            self.nodes.append(_node("Gather", [an, gi], [o],
                                    [_attr_int("axis", axis)]))
            self.min_opset = max(self.min_opset, 13)  # negative indices
            return o
        if opname == "scaled_dot_product_attention_ref":
            return self._emit_sdpa(args, kwargs, out_t, o)
        if opname in ("flatten", "reshape"):
            # static re-shape with a dynamic batch: Reshape with 0 in
            # dim 0 (ONNX: copy the input's dim) — only valid when the
            # op PRESERVES dim 0 (flatten(start_axis=0) / reshape([-1])
            # fold the batch in and must fall back)
            src = args[0]
            if not (isinstance(src, Tensor) and src.ndim >= 1
                    and out_t.ndim >= 1
                    and src.shape[0] == out_t.shape[0]):
                return None
            an = in_name(src)
            if an is None:
                return None
            tgt = [0] + [int(d) for d in out_t.shape[1:]]
            shp = self.add_init("shape", np.asarray(tgt, np.int64))
            self.nodes.append(_node("Reshape", [an, shp], [o]))
            return o
        return None

    def _emit_sdpa(self, args, kwargs, out_t, o):
        """scaled_dot_product_attention as an ONNX subgraph:
        Transpose -> MatMul -> Mul(scale) [-> Add(bias)] -> Softmax ->
        MatMul -> Transpose (inputs/outputs [B, T, H, Dh])."""
        q, k, v = args[0], args[1], args[2]
        attn_mask = kwargs.get("attn_mask",
                               args[3] if len(args) > 3 else None)
        is_causal = kwargs.get("is_causal",
                               args[5] if len(args) > 5 else False)
        if is_causal:
            return None  # causal mask: StableHLO path
        qn, kn, vn = (self.in_name(a, out_t) for a in (q, k, v))
        if qn is None or kn is None or vn is None:
            return None
        scale = kwargs.get("scale", args[6] if len(args) > 6 else None)
        if scale is None:
            scale = 1.0 / float(np.sqrt(q.shape[-1]))
        tp = lambda nm, perm: self._n(
            "Transpose", [nm], "tr", [_attr_ints("perm", perm)])
        qt = tp(qn, (0, 2, 1, 3))
        kt = tp(kn, (0, 2, 3, 1))
        vt = tp(vn, (0, 2, 1, 3))
        sc = self._n("Mul", [self._n("MatMul", [qt, kt], "qk"),
                             self.add_init("scale", np.float32(scale))],
                     "scaled")
        cur = sc
        if attn_mask is not None:
            from ..core.tensor import Tensor
            raw = (attn_mask.data if isinstance(attn_mask, Tensor)
                   else attn_mask)
            try:
                dt = getattr(raw, "dtype", None)
                if dt is None:  # python sequence: cheap probe
                    dt = np.asarray(raw).dtype
            except Exception:
                return None  # un-arrayable mask: StableHLO fallback
            if dt == np.bool_:
                # boolean mask is a where-select (-inf), NOT an additive
                # bias — exporting it as 0/1 Add would silently attend
                # masked positions; fall back
                return None
            mn = self.in_name(attn_mask, out_t)
            if mn is None:
                return None
            cur = self._n("Add", [cur, mn], "biased")
        sm = self._n("Softmax", [cur], "probs", [_attr_int("axis", -1)])
        self.min_opset = max(self.min_opset, 13)
        av = self._n("MatMul", [sm, vt], "attn")
        self.nodes.append(_node("Transpose", [av], [o],
                                [_attr_ints("perm", (0, 2, 1, 3))]))
        return o


def export(layer, path: str, input_spec=None, opset_version: int = _OPSET,
           **configs) -> str:
    """Export a Layer's traced graph (DAG, residual adds included) to a
    real .onnx file.

    Falls back to jit.save (StableHLO) with a warning when the model
    contains layers or graph shapes the ONNX emitter doesn't cover —
    deployment through inference.Config still works in that case.
    """
    from .. import nn, jit

    if input_spec is None:
        raise ValueError("onnx.export needs input_spec=[InputSpec(shape)] "
                         "to trace the model")
    spec = input_spec[0]
    decl_shape = [d if (d or 0) > 0 else None for d in spec.shape]
    shape = [d if d is not None else 1 for d in decl_shape]

    # Trace to an EVENT list (core/graph_trace.py — shared with the
    # inference passes): one event per supported leaf layer (the
    # structured emitters above), plus one event per FUNCTIONAL registry
    # op executed outside any leaf layer (the residual add, flatten(1),
    # F.relu glue in forward() bodies). Primitive ops fired INSIDE a
    # leaf layer are subsumed by that layer's event.
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core.graph_trace import trace_layer_graph
    in_dtype = jnp.dtype(str(getattr(spec, "dtype", "float32")))
    x = Tensor(jnp.zeros(tuple(shape), in_dtype))
    tr = trace_layer_graph(layer, x)
    events, traced_ids, y = tr.events, tr.traced_ids, tr.y

    obj_to_name = {id(x): "input"}
    em = _Emitter(names=obj_to_name, traced_ids=traced_ids)
    out_name = "input"
    supported = bool(events)
    for ev in events:
        if ev[0] == "layer":
            _, l, inputs, output = ev
            src = inputs[0] if isinstance(inputs, tuple) else inputs
            # in_name also bakes PRE-trace constants (e.g. position ids
            # an embedding layer consumes) as initializers
            x_name = em.in_name(src)
            if x_name is None:
                supported = False  # layer fed by an unnamed traced value
                break
            nm = em.emit(l, x_name)
            if nm is None:
                supported = False
                break
            obj_to_name[id(output)] = nm
            out_name = nm
        else:
            _, opname, args, kwargs, out = ev
            nm = em.emit_functional(opname, args, kwargs, out)
            if nm is None:
                supported = False
                break
            obj_to_name[id(out)] = nm
            out_name = nm
    # the model's return value must BE a traced output, or forward()
    # post-processing would be dropped
    if supported and id(y) in obj_to_name:
        out_name = obj_to_name[id(y)]
    else:
        supported = False
    if not supported or not events:
        import warnings
        jit.save(layer, path, input_spec=input_spec)
        warnings.warn(
            "onnx.export covers DAGs of Linear/Conv/BN/activation/pool "
            "layers plus elementwise/reshape glue; this model uses ops "
            "without an ONNX mapping — exported StableHLO to "
            f"{path}.pdmodel instead (paddle_tpu.inference loads it)")
        return path + ".pdmodel"

    graph = b"".join([
        *(P.field_message(1, n) for n in em.nodes),
        P.field_string(2, type(layer).__name__),
        *(P.field_message(5, t) for t in em.inits),
        P.field_message(11, _value_info("input", decl_shape,
                                        _elem_type(str(in_dtype)))),
        P.field_message(12, _value_info(
            out_name, [None if decl_shape[0] is None and i == 0 else int(d)
                       for i, d in enumerate(np.shape(y.data))],
            _elem_type(str(y.data.dtype)))),
    ])
    final_opset = max(opset_version, em.min_opset)
    opset = P.field_string(1, "") + P.field_varint(2, final_opset)
    model = b"".join([
        P.field_varint(1, _IR_VERSION),
        P.field_string(2, "paddle_tpu"),
        P.field_string(3, "0.3"),
        P.field_message(7, graph),
        P.field_message(8, opset),
    ])
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path

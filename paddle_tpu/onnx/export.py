"""Real ONNX export for layer chains.

Reference: python/paddle/onnx/export.py (delegates to paddle2onnx — a
full Program->ONNX compiler). This build has no onnx package, so the
exporter emits ModelProto in protobuf wire format directly (_proto.py)
for the layer types that cover the vision zoo and MLP-style models:
Linear, Conv2D, BatchNorm1D/2D, ReLU/ReLU6/Sigmoid/Tanh/Softmax/GELU/
LeakyReLU/Hardswish/Hardsigmoid, MaxPool2D, AvgPool2D,
AdaptiveAvgPool2D (global), Flatten, Dropout (eval identity),
PixelShuffle-free Sequential composition.

Layer call order is recorded with forward hooks on a tracing run; the
exporter requires a LINEAR chain (each layer consumes the previous
layer's output — true for Sequential-style models) and raises for
branching graphs, pointing at jit.save (StableHLO) for those.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from . import _proto as P

# onnx.proto field numbers (public spec)
_IR_VERSION = 8
_OPSET = 13

# TensorProto.DataType
_F32 = 1
_I64 = 7


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = _F32 if arr.dtype != np.int64 else _I64
    if dt == _F32:
        arr = arr.astype(np.float32)
    msg = b"".join([
        *(P.field_varint(1, int(d)) for d in arr.shape),   # dims
        P.field_varint(2, dt),                             # data_type
        P.field_string(8, name),                           # name
        P.field_bytes(9, arr.tobytes()),                   # raw_data
    ])
    return msg


def _value_info(name: str, shape, elem=_F32) -> bytes:
    dims = b"".join(
        P.field_message(1, P.field_varint(1, int(d)) if d is not None
                        else P.field_string(2, "N"))
        for d in shape)
    tensor_type = (P.field_varint(1, elem)
                   + P.field_message(2, dims))              # shape
    type_proto = P.field_message(1, tensor_type)            # tensor_type
    return P.field_string(1, name) + P.field_message(2, type_proto)


def _attr_int(name: str, v: int) -> bytes:
    return (P.field_string(1, name) + P.field_varint(3, v)
            + P.field_varint(20, 2))                        # type=INT


def _attr_ints(name: str, vs) -> bytes:
    return (P.field_string(1, name)
            + b"".join(P.field_varint(8, int(v)) for v in vs)
            + P.field_varint(20, 7))                        # type=INTS


def _attr_string(name: str, v: str) -> bytes:
    return (P.field_string(1, name) + P.field_bytes(4, v.encode())
            + P.field_varint(20, 3))                        # type=STRING


def _attr_float(name: str, v: float) -> bytes:
    import struct
    return (P.field_string(1, name)
            + P._varint(2 << 3 | 5) + struct.pack("<f", v)
            + P.field_varint(20, 1))                        # type=FLOAT


def _node(op_type: str, inputs, outputs, attrs: List[bytes] = (),
          name: str = "") -> bytes:
    return b"".join([
        *(P.field_string(1, i) for i in inputs),
        *(P.field_string(2, o) for o in outputs),
        P.field_string(3, name or outputs[0]),
        P.field_string(4, op_type),
        *(P.field_message(5, a) for a in attrs),
    ])


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


_OP_MIN_OPSET = {"Gelu": 20, "HardSwish": 14}


def _onnx_pads(pa):
    """paddle padding spec -> onnx pads (h0, w0, h1, w1); None when the
    spec (string SAME/VALID) has no static equivalent."""
    if isinstance(pa, str):
        return None
    if isinstance(pa, (tuple, list)) and len(pa) == 4:
        # paddle [h_lo, h_hi, w_lo, w_hi] -> onnx [h0, w0, h1, w1]
        return (pa[0], pa[2], pa[1], pa[3])
    if isinstance(pa, (tuple, list)) and len(pa) == 2 and \
            isinstance(pa[0], (tuple, list)):
        return (pa[0][0], pa[1][0], pa[0][1], pa[1][1])
    ph, pw = _pair(pa)
    return (ph, pw, ph, pw)


class _Emitter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.inits: List[bytes] = []
        self.counter = 0
        self.min_opset = 7

    def tname(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def add_init(self, base, arr):
        name = self.tname(base)
        self.inits.append(_tensor_proto(name, np.asarray(arr)))
        return name

    def emit(self, layer, x_name: str) -> Optional[str]:
        """Emit node(s) for `layer` consuming x_name; returns output
        name, or None if the layer type is unsupported."""
        from .. import nn
        t = type(layer).__name__
        out = self.tname(t.lower())
        if isinstance(layer, nn.Linear):
            w = self.add_init("weight", np.asarray(layer.weight.data))
            ins = [x_name, w]
            if layer.bias is not None:
                ins.append(self.add_init("bias",
                                         np.asarray(layer.bias.data)))
            # our weight layout is [in, out] = Gemm's B untransposed
            self.nodes.append(_node("Gemm", ins, [out]))
            return out
        if isinstance(layer, nn.Conv2D):
            w = self.add_init("weight", np.asarray(layer.weight.data))
            ins = [x_name, w]
            if layer.bias is not None:
                ins.append(self.add_init("bias",
                                         np.asarray(layer.bias.data)))
            st = _pair(layer.stride)
            pads = _onnx_pads(layer.padding)
            if pads is None:
                return None  # SAME/VALID: shape math differs; use jit.save
            di = _pair(layer.dilation)
            attrs = [_attr_ints("strides", st),
                     _attr_ints("pads", pads),
                     _attr_ints("dilations", di),
                     _attr_int("group", layer.groups)]
            self.nodes.append(_node("Conv", ins, [out], attrs))
            return out
        if isinstance(layer, (nn.BatchNorm1D, nn.BatchNorm2D)):
            nf = layer.num_features
            scale = self.add_init(
                "scale", np.asarray(layer.weight.data)
                if layer.weight is not None else np.ones(nf, np.float32))
            bias = self.add_init(
                "b", np.asarray(layer.bias.data)
                if layer.bias is not None else np.zeros(nf, np.float32))
            mean = self.add_init("mean", np.asarray(layer._mean.data))
            var = self.add_init("var", np.asarray(layer._variance.data))
            self.nodes.append(_node(
                "BatchNormalization", [x_name, scale, bias, mean, var],
                [out], [_attr_float("epsilon", float(layer.epsilon))]))
            return out
        simple = {"ReLU": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh",
                  "Hardswish": "HardSwish", "Hardsigmoid": "HardSigmoid"}
        if t in simple:
            self.nodes.append(_node(simple[t], [x_name], [out]))
            self.min_opset = max(self.min_opset, _OP_MIN_OPSET.get(
                simple[t], 7))
            return out
        if t == "GELU":
            approx = getattr(layer, "_kwargs", {}).get("approximate", False)
            self.nodes.append(_node(
                "Gelu", [x_name], [out],
                [_attr_string("approximate",
                              "tanh" if approx else "none")]))
            self.min_opset = max(self.min_opset, 20)
            return out
        if t == "Softmax":
            axis = getattr(layer, "_kwargs", {}).get("axis", -1)
            self.nodes.append(_node("Softmax", [x_name], [out],
                                    [_attr_int("axis", int(axis))]))
            self.min_opset = max(self.min_opset, 13)  # axis semantics
            return out
        if t == "Flatten":
            if getattr(layer, "stop_axis", -1) != -1:
                return None  # ONNX Flatten has only a start axis
            self.nodes.append(_node(
                "Flatten", [x_name], [out],
                [_attr_int("axis", int(getattr(layer, "start_axis", 1)))]))
            return out
        if t == "ReLU6":
            self.nodes.append(_node("Clip", [
                x_name, self.add_init("min", np.float32(0.0)),
                self.add_init("max", np.float32(6.0))], [out]))
            self.min_opset = max(self.min_opset, 11)  # min/max as inputs
            return out
        if t == "LeakyReLU":
            alpha = getattr(layer, "_kwargs", {}).get("negative_slope", 0.01)
            self.nodes.append(_node(
                "LeakyRelu", [x_name], [out],
                [_attr_float("alpha", float(alpha))]))
            return out
        if t in ("Dropout", "Dropout2D", "Dropout3D"):
            self.nodes.append(_node("Identity", [x_name], [out]))
            return out
        if isinstance(layer, (nn.MaxPool2D, nn.AvgPool2D)):
            pads = _onnx_pads(layer.padding)
            if pads is None:
                return None  # string/SAME padding: use the StableHLO path
            k = _pair(layer.kernel_size)
            st = _pair(layer.stride if layer.stride is not None
                       else layer.kernel_size)
            op = ("MaxPool" if isinstance(layer, nn.MaxPool2D)
                  else "AveragePool")
            self.nodes.append(_node(
                op, [x_name], [out],
                [_attr_ints("kernel_shape", k), _attr_ints("strides", st),
                 _attr_ints("pads", pads)]))
            return out
        if isinstance(layer, nn.AdaptiveAvgPool2D):
            if tuple(np.atleast_1d(layer.output_size)) in ((1,), (1, 1)):
                self.nodes.append(_node("GlobalAveragePool", [x_name],
                                        [out]))
                return out
            return None
        return None


def export(layer, path: str, input_spec=None, opset_version: int = _OPSET,
           **configs) -> str:
    """Export a Sequential-style Layer to a real .onnx file.

    Falls back to jit.save (StableHLO) with a warning when the model
    contains layers or graph shapes the ONNX emitter doesn't cover —
    deployment through inference.Config still works in that case.
    """
    from .. import nn, jit

    if input_spec is None:
        raise ValueError("onnx.export needs input_spec=[InputSpec(shape)] "
                         "to trace the model")
    spec = input_spec[0]
    decl_shape = [d if (d or 0) > 0 else None for d in spec.shape]
    shape = [d if d is not None else 1 for d in decl_shape]

    # record call order with hooks on a tracing forward
    calls = []
    hooks = []

    def rec(l, inputs, output):
        calls.append((l, inputs, output))

    leaves = [sub for _, sub in layer.named_sublayers(include_self=True)
              if not list(sub.sublayers())]
    for sub in leaves:
        hooks.append(sub.register_forward_post_hook(rec))
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    was_training = layer.training
    layer.eval()
    x = Tensor(jnp.zeros(tuple(shape), jnp.float32))
    try:
        y = layer(x)
    finally:
        if was_training:
            layer.train()
        for h in hooks:
            h.remove()

    em = _Emitter()
    out_name = "input"
    obj_to_name = {}
    supported = bool(calls)
    for ci, (l, inputs, output) in enumerate(calls):
        src = inputs[0] if isinstance(inputs, tuple) else inputs
        # linear chain check: the FIRST layer must consume the traced
        # input itself and every later layer the previous output —
        # otherwise functional pre/inter-processing in forward() would
        # be silently dropped from the graph
        if ci == 0:
            if src is not x:
                supported = False
                break
        elif id(src) not in obj_to_name:
            supported = False
            break
        cur_in = obj_to_name.get(id(src), "input")
        nm = em.emit(l, cur_in)
        if nm is None:
            supported = False
            break
        obj_to_name = {id(output): nm}
        out_name = nm
    # the model's return value must BE the last layer's output, or
    # forward() post-processing would be dropped
    if supported and id(y) not in obj_to_name:
        supported = False
    if not supported or not calls:
        import warnings
        jit.save(layer, path, input_spec=input_spec)
        warnings.warn(
            "onnx.export covers Sequential-style chains of "
            "Linear/Conv/BN/activation/pool layers; this model uses "
            "other shapes — exported StableHLO to "
            f"{path}.pdmodel instead (paddle_tpu.inference loads it)")
        return path + ".pdmodel"

    graph = b"".join([
        *(P.field_message(1, n) for n in em.nodes),
        P.field_string(2, type(layer).__name__),
        *(P.field_message(5, t) for t in em.inits),
        P.field_message(11, _value_info("input", decl_shape)),
        P.field_message(12, _value_info(
            out_name, [None if decl_shape[0] is None and i == 0 else int(d)
                       for i, d in enumerate(np.shape(y.data))])),
    ])
    final_opset = max(opset_version, em.min_opset)
    opset = P.field_string(1, "") + P.field_varint(2, final_opset)
    model = b"".join([
        P.field_varint(1, _IR_VERSION),
        P.field_string(2, "paddle_tpu"),
        P.field_string(3, "0.3"),
        P.field_message(7, graph),
        P.field_message(8, opset),
    ])
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path

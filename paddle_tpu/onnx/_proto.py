"""Minimal protobuf wire-format writer/reader for ONNX emission.

The environment ships no `onnx` package, so paddle_tpu.onnx.export
serializes ModelProto directly in protobuf wire format (varints +
length-delimited submessages — the stable part of protobuf). Field
numbers follow the public onnx.proto (onnx/onnx.proto, IR version 8).
"""
from __future__ import annotations

from typing import List, Tuple, Union


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3 | 0) + _varint(value)


def field_bytes(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def field_string(num: int, s: str) -> bytes:
    return field_bytes(num, s.encode("utf-8"))


def field_message(num: int, encoded: bytes) -> bytes:
    return field_bytes(num, encoded)


# -- reader (for round-trip tests) -----------------------------------------

def parse(buf: bytes) -> List[Tuple[int, int, Union[int, bytes]]]:
    """[(field_number, wire_type, value)] — value is int for varint
    fields, bytes for length-delimited."""
    out = []
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
            out.append((fnum, wt, v))
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            out.append((fnum, wt, buf[i:i + ln]))
            i += ln
        elif wt == 5:  # 32-bit
            out.append((fnum, wt, buf[i:i + 4]))
            i += 4
        elif wt == 1:  # 64-bit
            out.append((fnum, wt, buf[i:i + 8]))
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
    return out


def _read_varint(buf: bytes, i: int):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def fields(buf: bytes, num: int):
    return [v for f, _, v in parse(buf) if f == num]

"""paddle.callbacks namespace (reference: python/paddle/hapi/callbacks.py
exported as paddle.callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
    LRScheduler,
)

"""Static-analysis subsystem: jaxpr lint passes + paged-KV invariant
checker for the serving stack.

The JAX-native counterpart of the reference's IR pass infrastructure
and runtime enforcement (``paddle/pir``, ``phi/core/enforce.h``):
analysis over **jaxprs** (the IR every program here already lowers
through) and over the serving stack's host-side state. Entry points:

* ``tools/graph_lint.py`` — CLI running every pass over the flagship
  llama + qwen2_moe serving graphs (the pre-merge check).
* ``ServingEngine(check_invariants=True)`` — per-tick paged-KV
  invariant checking (race-detector-style debug mode).
* ``audit_engine(engine)`` — standalone audit of a live engine.

See docs/ANALYSIS.md for each pass's invariant and how to add one.
"""
from .collectives import (CollectiveConsistencyPass,
                          check_stage_consistency,
                          collective_signature)
from .dtype_drift import DtypeDriftPass
from .framework import (Finding, GraphTarget, LintPass, LintReport,
                        Severity, run_passes, trace_graph)
from .host_sync import HostSyncPass
from .kv_invariants import (KVInvariantError, Violation,
                            audit_defrag_plan, audit_engine,
                            audit_serving_state)
from .recompile import (RecompileHazardPass, ServingGeometry,
                        enumerate_chunk_programs)
from .serving_graphs import (engine_geometry, pp_stage_targets,
                             serving_targets)

__all__ = [
    "CollectiveConsistencyPass", "DtypeDriftPass", "Finding",
    "GraphTarget", "HostSyncPass", "KVInvariantError", "LintPass",
    "LintReport", "RecompileHazardPass", "ServingGeometry", "Severity",
    "Violation", "audit_defrag_plan", "audit_engine",
    "audit_serving_state", "check_stage_consistency",
    "collective_signature", "engine_geometry",
    "enumerate_chunk_programs", "pp_stage_targets", "run_passes",
    "serving_targets", "trace_graph",
]

"""Static-analysis subsystem: jaxpr lint passes + paged-KV invariant
checker for the serving AND training stacks.

The JAX-native counterpart of the reference's IR pass infrastructure
and runtime enforcement (``paddle/pir``, ``phi/core/enforce.h``):
analysis over **jaxprs** (the IR every program here already lowers
through) and over the serving stack's host-side state. Entry points:

* ``tools/graph_lint.py`` — CLI running every pass over the flagship
  llama + qwen2_moe serving graphs and the llama train-step graphs at
  the dp / dp×mp / pp(1F1B) / zero-sharded geometries (the pre-merge
  check).
* ``tools/auto_parallel.py`` — the auto-parallel planner
  (``analysis/planner.py``): search + rank the legal
  (dp, tp, pp, V, M, schedule, zero, dtype) space with a composed
  static cost model, then trace-verify the winner through the full
  pass stack under the ``planner-contract`` tolerance.
* ``ServingEngine(check_invariants=True)`` — per-tick paged-KV
  invariant checking (race-detector-style debug mode).
* ``graph_lint --suite concurrency`` — the host-side concurrency
  analysis (``analysis/concurrency.py``): static guarded-by lint +
  lock-order cycle detection over every lock in
  ``paddle_tpu/serving/``, paired with the runtime ``LockTracer`` and
  seeded schedule fuzzer (``serving/locktrace.py``).
* ``graph_lint --suite kernels`` — the Pallas kernel auditor
  (``analysis/kernel_audit.py``): static VMEM-footprint, grid/index-
  map, DMA-discipline, and accumulator-dtype proofs (KA001–KA004)
  over every registered kernel geometry plus every swept winner in
  the autotune store; the same verdict gates autotune admission
  (``ops.autotune.record(audit=True)``, audited ``lookup``).
* ``audit_engine(engine)`` — standalone audit of a live engine;
  ``audit_engine_plan(engine)`` — mpu-hint audit of an auto-parallel
  Engine's plan; ``Engine.donation_audit()`` — donation audit of the
  live jitted train step.

See docs/ANALYSIS.md for each pass's invariant and how to add one.
"""
from .concurrency import (analyze_source, analyze_tree, check_tree,
                          fuzz_fleet_scenario, mutate_remove_with)
from .collectives import (CollectiveConsistencyPass,
                          check_stage_consistency,
                          collective_cost_bytes, collective_signature,
                          scan_trip_counts)
from .donation import DonationAuditPass, jit_donation_flags
from .dtype_drift import DtypeDriftPass
from .framework import (ExactnessContract, Finding, GraphTarget,
                        LintPass, LintReport, PASS_REGISTRY,
                        REWRITE_REGISTRY, RewritePass, Severity,
                        default_passes, default_rewrites,
                        register_pass, register_rewrite, run_passes,
                        trace_graph)
from .hbm import (HbmEstimate, HbmPeakPass, estimate_hbm_peak,
                  xla_cost_analysis, xla_peak_bytes)
from .host_sync import HostSyncPass
from .kernel_audit import (ALL_RULES as KERNEL_AUDIT_RULES,
                           GATE_RULES as KERNEL_AUDIT_GATE_RULES,
                           KernelAuditError, KernelSpec,
                           VMEM_AUDIT_BUDGET, Waiver, audit_callable,
                           audit_config, audit_kernel,
                           kernel_signatures, run_kernel_audit)
from .kv_invariants import (KVInvariantError, Violation,
                            audit_defrag_plan, audit_engine,
                            audit_serving_state)
from .planner import (CostModel, PlanCost, PlanPoint,
                      PlannerContractPass, enumerate_plan_points,
                      plan_auto_parallel, price_plan_point,
                      verify_plan)
from .recompile import (RecompileHazardPass, ServingGeometry,
                        enumerate_chunk_programs,
                        enumerate_tick_programs)
from .rewrite import (FusedRmsNormPass, Int8EpilogueFusePass,
                      RewriteResult, VerifyOutcome, count_matches,
                      rewrite_callable, rewrite_jaxpr, rewrite_target,
                      run_rewrite_suite, verify_rewrite, verify_site)
from .serving_graphs import (engine_geometry, pp_stage_targets,
                             rewrite_targets, serving_targets)
from .sharding_lint import (ShardingLintPass, audit_engine_plan,
                            spec_shard_factor)
from .training_graphs import (TRAIN_GEOMETRIES, build_train_target,
                              flagship_train_objects,
                              train_stage_targets, train_step_target,
                              training_targets)

__all__ = [
    "CollectiveConsistencyPass", "CostModel", "DonationAuditPass",
    "DtypeDriftPass",
    "ExactnessContract", "Finding", "FusedRmsNormPass", "GraphTarget",
    "HbmEstimate", "HbmPeakPass", "HostSyncPass",
    "Int8EpilogueFusePass", "KERNEL_AUDIT_GATE_RULES",
    "KERNEL_AUDIT_RULES", "KVInvariantError", "KernelAuditError",
    "KernelSpec", "LintPass",
    "LintReport", "PASS_REGISTRY", "PlanCost", "PlanPoint",
    "PlannerContractPass", "REWRITE_REGISTRY",
    "RecompileHazardPass", "RewritePass", "RewriteResult",
    "ServingGeometry", "Severity", "ShardingLintPass",
    "TRAIN_GEOMETRIES", "VMEM_AUDIT_BUDGET", "VerifyOutcome",
    "Violation", "Waiver",
    "analyze_source", "analyze_tree", "audit_callable",
    "audit_config", "audit_defrag_plan", "audit_engine",
    "audit_engine_plan", "audit_kernel",
    "audit_serving_state", "build_train_target", "check_tree",
    "check_stage_consistency", "collective_cost_bytes",
    "collective_signature", "count_matches", "default_passes",
    "default_rewrites", "engine_geometry", "enumerate_chunk_programs",
    "enumerate_plan_points", "enumerate_tick_programs",
    "estimate_hbm_peak", "flagship_train_objects",
    "fuzz_fleet_scenario", "jit_donation_flags", "kernel_signatures",
    "mutate_remove_with", "plan_auto_parallel", "pp_stage_targets",
    "price_plan_point", "register_pass",
    "register_rewrite", "rewrite_callable", "rewrite_jaxpr",
    "rewrite_target", "rewrite_targets", "run_passes",
    "run_kernel_audit",
    "run_rewrite_suite", "scan_trip_counts", "serving_targets",
    "spec_shard_factor", "trace_graph", "train_stage_targets",
    "train_step_target", "training_targets", "verify_plan",
    "verify_rewrite", "verify_site", "xla_cost_analysis",
    "xla_peak_bytes",
]

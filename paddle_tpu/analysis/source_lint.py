"""AST-level source lint: the ruff-shaped subset that runs anywhere.

The pre-merge check is ``ruff check`` (configured in pyproject.toml) —
but ruff is a rust binary the runtime container does not ship, and a
pre-merge gate that silently no-ops when its linter is missing is the
vacuous-pass anti-pattern. This module implements the highest-signal
rules with the stdlib ``ast`` so ``tools/graph_lint.py --ci`` always
lints source, with ruff layered on top when available:

* **unused-import** (F401): a module-level import never referenced.
  Conservative by construction — names re-exported via ``__all__``,
  imports under ``try``/``except`` (version shims), ``__future__``,
  and any textual use (docstring examples excluded) are kept; only
  imports with zero occurrences anywhere else in the file flag.
* **none-compare** (E711): ``== None`` / ``!= None``.
* **bare-except** (E722): ``except:`` catching BaseException silently.
* **mutable-default** (B006): ``def f(x=[])`` / ``{}`` / ``set()``.
* **unused-local** (F841): a local bound by a plain ``name = ...``
  assignment (or ``except ... as name``) and never read anywhere in
  the function — including nested closures. Conservative: tuple
  unpacking, augmented assignment, underscore-prefixed names and
  ``global``/``nonlocal`` names never flag (matching ruff's default
  F841 scope; an unused loop variable is B007's business, not ours).
* **table-width VMEM scratch** (PT004) — *Pallas kernels only*
  (``ops/pallas/``): a ``pltpu.VMEM(...)`` scratch shape whose
  expression references ``pps`` / ``pages_per_slot``. Scratch that
  scales with the page-table WIDTH caps context length by VMEM — the
  failure mode the r16 tiled flash combine exists to remove — so only
  the explicitly one-shot kernel path may do it, behind a
  ``# noqa: PT004`` with a justification. This is the CI guard that
  the 100k-token ceiling cannot silently regress: a new kernel (or an
  edit to the tiled one) that re-introduces O(pages_per_slot) scratch
  fails ``graph_lint --ci`` at the source level.
* **serving hot-path host sync** (PT005) — *serving code only*
  (``paddle_tpu/serving/``): the idioms that silently serialize the
  tick loop on a device→host round-trip — ``.item()`` on anything,
  and bare single-argument ``np.asarray(x)`` / ``np.array(x)`` (the
  device-pull shape: converting a host container passes a dtype,
  pulling a tick result does not). The engine's sanctioned pull sites — THE per-tick token
  read-back, which must sync by design — carry
  ``# noqa: PT005`` with a justification; everything else in the
  serving tree is a hot path where an extra sync is the
  [S,V]-logits-pull bug class all over again.
* **thread attribution** (CC002) — *library code only*
  (``paddle_tpu/``): every ``threading.Thread(...)`` must pass
  ``name=`` and ``daemon=`` explicitly. The concurrency analysis
  (analysis/concurrency.py) attributes lock traces, inversion records
  and flight-recorder postmortems by thread name — an anonymous
  ``Thread-7`` in a postmortem is unactionable. Reasoned suppression:
  ``# noqa: CC002(reason)``; a CC-series noqa WITHOUT a reason flags
  as CC004 (the concurrency pass owns that check inside
  ``paddle_tpu/serving/``, this lint covers the rest of the tree).
* **host-sync** (PT001/PT002/PT003) — *library code only*
  (``paddle_tpu/``; tools and tests, which legitimately pull results
  to the host, are exempt): the source-level companion of the
  host-sync GRAPH pass (analysis/host_sync.py). ``jax.device_get``
  (PT001) and ``.block_until_ready()`` (PT002) calls, and
  ``float(...)``/``bool(...)`` coercions whose argument involves a
  ``jnp``/``jax``/``lax`` expression (PT003) — each is a device→host
  round-trip that serializes the dispatch pipeline (the
  GradScaler-per-param and [S,V]-logits bug classes). Deliberate
  syncs (a ``synchronize()`` API, a timing harness) carry
  ``# noqa: PT00x`` with a justification. The PT003 heuristic is
  conservative by construction: coercions of locals it cannot prove
  jax-rooted do not flag.

Scope: ``paddle_tpu/`` and ``tools/`` (tests use pytest fixtures whose
"unused" imports are the fixture mechanism).
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path
from typing import List, Tuple

__all__ = ["lint_file", "lint_tree"]


def _import_names(node) -> List[Tuple[str, str]]:
    """(bound_name, display) pairs one import statement binds."""
    out = []
    if isinstance(node, ast.Import):
        for a in node.names:
            bound = a.asname or a.name.split(".")[0]
            out.append((bound, a.name))
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return []
        for a in node.names:
            if a.name == "*":
                return []
            out.append((a.asname or a.name, a.name))
    return out


def _code_text_without_import_lines(src: str, tree) -> str:
    """Source with module-level import statements and comments blanked
    — what a name must appear in to count as 'used'."""
    lines = src.splitlines()
    drop = set()
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for i in range(node.lineno, (node.end_lineno or
                                         node.lineno) + 1):
                drop.add(i)
    kept = [("" if i + 1 in drop else ln)
            for i, ln in enumerate(lines)]
    text = "\n".join(kept)
    # strip comments (a name in a comment is not a use)
    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        spans = [(t.start, t.end) for t in toks
                 if t.type == tokenize.COMMENT]
    except tokenize.TokenError:
        spans = []
    if spans:
        out = text.splitlines()
        for (r0, c0), (_, c1) in spans:
            ln = out[r0 - 1]
            out[r0 - 1] = ln[:c0] + " " * (c1 - c0) + ln[c1:]
        text = "\n".join(out)
    return text


_NOQA = re.compile(r"#\s*noqa(?::\s*(?P<codes>.*))?", re.IGNORECASE)
_NOQA_CODE = re.compile(r"\s*([A-Za-z][A-Za-z0-9]*)"
                        r"(?:\(([^)]*)\))?\s*")


def _parse_noqa_codes(s: str) -> dict:
    """``"F401,E711"`` / ``"PT005 — text"`` / ``"CC001(reason)"`` ->
    {code: reason-or-None}. Stops at the first token that is not a
    (possibly reasoned) code — the trailing ``— free text`` of the
    legacy form is ignored, and a hyphen INSIDE a ``(reason)`` does
    not truncate it."""
    out, pos = {}, 0
    while pos < len(s):
        m = _NOQA_CODE.match(s, pos)
        if m is None:
            break
        out[m.group(1).upper()] = (
            m.group(2).strip() if m.group(2) else None)
        pos = m.end()
        if pos < len(s) and s[pos] == ",":
            pos += 1
        else:
            break
    return out


def _noqa_map(src: str):
    """lineno -> {code: reason-or-None} (empty dict = suppress all).

    Accepts the legacy ``# noqa: F401,E711`` and ``# noqa: PT005 —
    text`` forms plus the CC-series reasoned form ``# noqa:
    CC001(why this lock-free access is safe)``."""
    out = {}
    for i, ln in enumerate(src.splitlines(), start=1):
        m = _NOQA.search(ln)
        if m:
            codes = m.group("codes")
            out[i] = _parse_noqa_codes(codes) if codes else {}
    return out


def lint_file(path: Path, src: str = None,
              host_sync_scope: bool = False,
              pallas_scope: bool = False,
              serving_scope: bool = False) -> List[Tuple]:
    """[(rule, lineno, message)] for one file. ``# noqa`` (optionally
    ``# noqa: F401,E711``) on the statement's first line suppresses.
    ``host_sync_scope=True`` (library code under ``paddle_tpu/``)
    additionally runs the PT00x host-sync rules AND the CC002
    thread-attribution rule; ``pallas_scope=True`` (``ops/pallas/``)
    the PT004 VMEM-scratch rule; ``serving_scope=True``
    (``paddle_tpu/serving/``) the PT005 hot-path host-sync rule."""
    if src is None:
        src = Path(path).read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [("E999", e.lineno or 0, f"syntax error: {e.msg}")]
    findings: List[Tuple] = []
    name = Path(path).name
    noqa = _noqa_map(src)

    def suppressed(rule: str, line: int) -> bool:
        codes = noqa.get(line)
        return codes is not None and (not codes or rule in codes)

    # ---- unused module-level imports (skip __init__ re-export files) -
    if name != "__init__.py":
        guarded = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Try):
                for n in ast.walk(node):
                    if isinstance(n, (ast.Import, ast.ImportFrom)):
                        guarded.add(id(n))
        body_text = _code_text_without_import_lines(src, tree)
        exported = set()
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "__all__"
                            for t in node.targets)):
                try:
                    exported |= set(ast.literal_eval(node.value))
                except (ValueError, TypeError):
                    pass
        for node in tree.body:
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if id(node) in guarded:
                continue
            for bound, display in _import_names(node):
                if bound in exported or bound.startswith("_"):
                    continue
                if re.search(rf"\b{re.escape(bound)}\b", body_text):
                    continue
                if suppressed("F401", node.lineno):
                    continue
                findings.append((
                    "F401", node.lineno,
                    f"`{display}` imported as `{bound}` but unused"))

    # ---- unused locals (F841) ---------------------------------------
    def _own_statements(fn):
        """Nodes belonging to ``fn`` itself — nested function/lambda/
        class bodies excluded (their assignments are their own scope:
        a nested class's attribute binding is read via attribute
        access, which name-level analysis cannot see)."""
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        loaded, external = set(), set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Load, ast.Del)):
                loaded.add(n.id)  # closures in nested defs count
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                external |= set(n.names)
        binds = []  # (name, lineno)
        for n in _own_statements(fn):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)):
                binds.append((n.targets[0].id, n.lineno))
            elif isinstance(n, ast.ExceptHandler) and n.name:
                binds.append((n.name, n.lineno))
        for bound, line in binds:
            if (bound.startswith("_") or bound in external
                    or bound in loaded or suppressed("F841", line)):
                continue
            findings.append((
                "F841", line,
                f"local `{bound}` in `{fn.name}()` is assigned but "
                f"never used"))

    # ---- table-width VMEM scratch in Pallas kernels (PT004) ---------
    if pallas_scope:
        _WIDTH_NAMES = {"pps", "pages_per_slot"}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "VMEM" and node.args):
                continue
            used = {n.id for n in ast.walk(node.args[0])
                    if isinstance(n, ast.Name)}
            if used & _WIDTH_NAMES and not suppressed("PT004",
                                                      node.lineno):
                findings.append((
                    "PT004", node.lineno,
                    "VMEM scratch shape scales with the page-table "
                    "width (pages_per_slot) — this caps context "
                    "length by VMEM; walk KV in O(tile) scratch (the "
                    "tiled flash combine) or noqa the explicitly "
                    "one-shot path with a justification"))

    # ---- serving hot-path host syncs (PT005) ------------------------
    if serving_scope:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "item"
                    and not node.args and not node.keywords):
                if not suppressed("PT005", node.lineno):
                    findings.append((
                        "PT005", node.lineno,
                        "`.item()` in serving hot-path code — a "
                        "blocking per-value device→host pull; batch "
                        "the read-back (one np.asarray at the "
                        "sanctioned pull site) or keep the value "
                        "device-side"))
            elif (isinstance(f, ast.Attribute)
                  and f.attr in ("asarray", "array")
                  and isinstance(f.value, ast.Name)
                  and f.value.id in ("np", "numpy")
                  and len(node.args) == 1 and not node.keywords):
                # a dtype argument marks a host-container conversion;
                # the bare single-arg form is the device-pull shape
                if not suppressed("PT005", node.lineno):
                    findings.append((
                        "PT005", node.lineno,
                        f"bare `np.{f.attr}(...)` in serving hot-path "
                        "code — if the argument is a device value "
                        "this is a blocking sync; pull once at the "
                        "sanctioned site (# noqa: PT005 with a "
                        "justification) or pass a dtype if this "
                        "converts a host container"))

    # ---- thread attribution in library code (CC002) -----------------
    # Unnamed threads make tracer spans, flight-recorder postmortems
    # and LockTracer inversion records unattributable; an implicit
    # daemon flag makes shutdown behaviour an accident of the default.
    if host_sync_scope:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_thread = (
                (isinstance(f, ast.Attribute) and f.attr == "Thread"
                 and isinstance(f.value, ast.Name)
                 and f.value.id == "threading")
                or (isinstance(f, ast.Name) and f.id == "Thread"))
            if not is_thread:
                continue
            kw = {k.arg for k in node.keywords}
            missing = [k for k in ("name", "daemon") if k not in kw]
            if missing and not suppressed("CC002", node.lineno):
                findings.append((
                    "CC002", node.lineno,
                    "threading.Thread(...) without explicit "
                    + " and ".join(f"{k}=" for k in missing)
                    + " — unnamed/implicit threads make tracer spans "
                    "and postmortems unattributable"))
        # reasonless CC-series noqa (CC004). The concurrency pass owns
        # this check for serving files (it sees guarded-by context);
        # source_lint covers the rest of the library tree.
        if not serving_scope:
            for line, codes in sorted(noqa.items()):
                for code, reason in codes.items():
                    if (code.startswith("CC") and code != "CC004"
                            and not reason):
                        findings.append((
                            "CC004", line,
                            f"# noqa: {code} without a justification — "
                            f"write # noqa: {code}(reason)"))

    # ---- host syncs in library code (PT001/PT002/PT003) -------------
    if host_sync_scope:
        def _jax_rooted(expr) -> bool:
            return any(isinstance(n, ast.Name)
                       and n.id in ("jnp", "jax", "lax")
                       for n in ast.walk(expr))

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if ((isinstance(f, ast.Attribute) and f.attr == "device_get")
                    or (isinstance(f, ast.Name)
                        and f.id == "device_get")):
                if not suppressed("PT001", node.lineno):
                    findings.append((
                        "PT001", node.lineno,
                        "`jax.device_get` in library code — a "
                        "device→host transfer; return the array and "
                        "let the caller decide when to sync"))
            elif (isinstance(f, ast.Attribute)
                  and f.attr == "block_until_ready"):
                if not suppressed("PT002", node.lineno):
                    findings.append((
                        "PT002", node.lineno,
                        "`.block_until_ready()` in library code — "
                        "serializes the dispatch pipeline; only a "
                        "timing harness or an explicit synchronize() "
                        "API should do this (noqa with justification)"))
            elif (isinstance(f, ast.Name) and f.id in ("float", "bool")
                  and len(node.args) == 1 and not node.keywords
                  and _jax_rooted(node.args[0])):
                if not suppressed("PT003", node.lineno):
                    findings.append((
                        "PT003", node.lineno,
                        f"`{f.id}()` coercion of a jax expression — a "
                        "blocking host pull per call (the GradScaler-"
                        "per-param bug class); keep the value device-"
                        "side or sync once, fused"))

    for node in ast.walk(tree):
        # ---- == None / != None ----------------------------------
        if isinstance(node, ast.Compare):
            for op, cmp_ in zip(node.ops, node.comparators):
                if (isinstance(op, (ast.Eq, ast.NotEq))
                        and isinstance(cmp_, ast.Constant)
                        and cmp_.value is None
                        and not suppressed("E711", node.lineno)):
                    kind = "==" if isinstance(op, ast.Eq) else "!="
                    findings.append((
                        "E711", node.lineno,
                        f"comparison `{kind} None` — use "
                        f"`is{' not' if kind == '!=' else ''} None`"))
        # ---- bare except ----------------------------------------
        if (isinstance(node, ast.ExceptHandler) and node.type is None
                and not suppressed("E722", node.lineno)):
            findings.append((
                "E722", node.lineno,
                "bare `except:` — catch a concrete exception (or "
                "`Exception`)"))
        # ---- mutable default args -------------------------------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]:
                if suppressed("B006", node.lineno):
                    continue
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(d, ast.Call)
                        and isinstance(d.func, ast.Name)
                        and d.func.id in ("list", "dict", "set")
                        and not d.args and not d.keywords):
                    findings.append((
                        "B006", node.lineno,
                        f"mutable default argument in "
                        f"`{node.name}()` — shared across calls"))
    return findings


def lint_tree(root: Path, subdirs=("paddle_tpu", "tools")
              ) -> List[Tuple]:
    """[(path, rule, lineno, message)] over the repo's lintable set."""
    root = Path(root)
    out: List[Tuple] = []
    for sub in subdirs:
        base = root / sub
        if not base.exists():
            continue
        for p in sorted(base.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            for rule, line, msg in lint_file(
                    p, host_sync_scope=(sub == "paddle_tpu"),
                    pallas_scope=("pallas" in p.parts),
                    serving_scope=("serving" in p.parts)):
                out.append((str(p.relative_to(root)), rule, line, msg))
    return out

"""Per-region ResNet profile: the measurement behind the conv rewrites.

``tools/resnet_bench.py --profile`` calls :func:`profile_resnet`, which
answers "where does the step go, and what do the rewrite passes do to
it" with numbers instead of intuition:

* **Regions come from the matcher, not a hand-list.** Every site the
  conv rewrite passes match (``_Rewriter.sites``) IS a profiled region
  — the matched sub-jaxpr is lifted into its own callable and compiled,
  so the baseline cost is exactly the subgraph the pass deletes and the
  rewritten cost is exactly the replacement it installs. A profile row
  can never drift out of sync with what the passes actually do.
* **Costs are XLA's own.** flops/bytes per region are the compiled
  region's ``cost_analysis`` (the optimized-HLO cost model), and ms is
  slope-timed (run n1 and n0 iterations, take ``(t1-t0)/(n1-n0)`` —
  dispatch overhead cancels).
* **Two honesty caveats are reported, not hidden.** (1) Region-level
  bytes overstate what a whole-graph compile saves — XLA already fuses
  elementwise chains into the conv when it compiles the full model, so
  the JSON carries BOTH the per-region sums and the full-graph A/B.
  (2) On CPU the full-graph cost-model bytes barely move (~1.01x) for
  exactly that reason; the per-region table is the claim's evidence,
  the full-graph numbers bound it from below.

The JSON schema (stable; docs/PERF.md quotes it):

``{"metric": "resnet<depth>_per_region_profile", "regions": [{"name",
"rule", "count", "flops", "bytes", "ms", "pct_of_step", "rewritten":
{"flops", "bytes", "ms"}}], "totals": {"baseline", "rewritten",
"bytes_ratio", "ms_ratio"}, "full_graph": {...}, "step_ms", ...}``
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["profile_resnet", "region_name"]


def _slope_ms(fn, args, n0: int = 1, n1: int = 5, reps: int = 2) -> float:
    """Best-of-``reps`` slope time of ``fn(*args)`` in milliseconds."""
    import jax
    out = fn(*args)
    jax.block_until_ready(out)  # noqa: PT002 — timing harness
    t = {}
    for n in (n0, n1):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn(*args)
            jax.block_until_ready(out)  # noqa: PT002 — timing harness
            best = min(best, time.perf_counter() - t0)
        t[n] = best
    return max((t[n1] - t[n0]) / (n1 - n0), 0.0) * 1e3


def _sub_jaxpr_fn(level, m):
    """Lift one matched site into its own jitted callable. Returns
    ``(fn, external_invars)`` — the site's equations become a fresh
    Jaxpr whose inputs are the values flowing into the match from
    outside (literals stay inline)."""
    import jax
    from jax._src import core as jax_core
    idxs = sorted(m.eqn_idxs)
    eqns = [level.eqns[i] for i in idxs]
    produced = {o for e in eqns for o in e.outvars}
    external: List[Any] = []
    for e in eqns:
        for a in e.invars:
            if (not isinstance(a, jax_core.Literal) and a not in produced
                    and a not in external):
                external.append(a)
    sub = jax_core.Jaxpr(constvars=[], invars=list(external),
                         outvars=list(m.out_vars), eqns=eqns)
    closed = jax_core.ClosedJaxpr(sub, [])
    return jax.jit(jax_core.jaxpr_as_fun(closed)), external


def _unfused_cost(level, m) -> Dict[str, float]:
    """Per-op accounting of one matched site: every equation compiled
    as its OWN kernel (lowered from avals — no execution), costs
    summed. This is the traffic the unfused idiom pays under per-op
    (eager) execution — one activation round-trip per elementwise op —
    and the accounting under which the fusion claim is measured; the
    fused-region numbers alongside show what XLA's own fusion already
    recovers when it gets the whole region in one compile."""
    import jax
    from jax._src import core as jax_core
    tot = {"flops": 0.0, "bytes": 0.0}
    for i in sorted(m.eqn_idxs):
        eqn = level.eqns[i]
        # literal operands stay inline in the single-eqn jaxpr; only
        # (unique) Vars become invars
        arg_atoms = list(dict.fromkeys(
            a for a in eqn.invars
            if not isinstance(a, jax_core.Literal)))
        sub = jax_core.Jaxpr(constvars=[], invars=list(arg_atoms),
                             outvars=list(eqn.outvars), eqns=[eqn])
        fn = jax.jit(jax_core.jaxpr_as_fun(jax_core.ClosedJaxpr(sub, [])))
        specs = [jax.ShapeDtypeStruct(a.aval.shape, a.aval.dtype)
                 for a in arg_atoms]
        try:
            comp = fn.lower(*specs).compile()
        except Exception:
            continue
        c = _cost(comp)
        tot["flops"] += c["flops"]
        tot["bytes"] += c["bytes"]
    return tot


def region_name(m) -> str:
    """Readable geometry key: ``conv7x7s2_3->64@224x224`` (+``_relu``)."""
    x = m.bindings["x"].aval
    w = m.bindings["w"].aval
    kh, kw = int(w.shape[2]), int(w.shape[3])
    s = m.statics.get("strides", (1, 1))
    tag = f"conv{kh}x{kw}s{s[0]}_{int(w.shape[1])}->{int(w.shape[0])}" \
          f"@{int(x.shape[2])}x{int(x.shape[3])}"
    if m.statics.get("relu"):
        tag += "_relu"
    return tag


def _geom_key(rule, m):
    x, w = m.bindings["x"].aval, m.bindings["w"].aval
    return (rule.name, tuple(x.shape), str(x.dtype), tuple(w.shape),
            m.statics.get("strides"), m.statics.get("padding"),
            m.statics.get("dilation"), m.statics.get("groups"),
            m.statics.get("relu"))


def _cost(compiled) -> Dict[str, float]:
    from .hbm import xla_cost_analysis
    ca = xla_cost_analysis(compiled)
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}


def profile_resnet(depth: int = 50, image: int = 224, batch: int = 8,
                   mode: str = "infer",
                   rules: Optional[Sequence[Any]] = None,
                   reps: int = 2) -> Dict[str, Any]:
    """Per-region baseline-vs-rewritten profile of one ResNet forward.

    ``mode="infer"`` profiles the inference graph (conv-bn-fold regions
    — the fold subsumes the layout and space-to-depth transforms);
    ``mode="train"`` profiles the train-mode forward (stem + layout
    regions; the fold is structurally blocked by the batch-stat
    escapes). Regions are timed and cost-analyzed on seeded inputs of
    the site's exact avals — values don't change timing or the cost
    model, and it avoids an eager full-graph evaluation."""
    import jax

    from .framework import default_rewrites
    from .rewrite import _Rewriter, _seed_value, rewrite_target
    from .rewrite_conv import resnet_rewrite_targets

    rules = list(rules) if rules is not None else default_rewrites()
    targets = resnet_rewrite_targets(depth=depth, image=image,
                                     batch=batch)
    target = {"infer": targets[0], "train": targets[1]}[mode]

    rw = _Rewriter(rules)
    rng = np.random.RandomState(0)
    groups: Dict[Any, Dict[str, Any]] = {}
    for level, rule, m in rw.sites(target.jaxpr.jaxpr):
        if "x" not in m.bindings or "w" not in m.bindings:
            continue                      # not a conv region (decode tail)
        key = _geom_key(rule, m)
        if key in groups:
            groups[key]["count"] += 1
            continue
        groups[key] = {"rule": rule, "m": m, "level": level, "count": 1}

    regions: List[Dict[str, Any]] = []
    for key, g in groups.items():
        rule, m, level = g["rule"], g["m"], g["level"]
        base_fn, external = _sub_jaxpr_fn(level, m)
        seeded = {v: jax.device_put(_seed_value(v.aval, rng))
                  for v in external}
        base_args = [seeded[v] for v in external]
        base_comp = base_fn.lower(*base_args).compile()
        rew_args = [seeded[m.bindings[n]] for n in rule.arg_names]
        rew_fn = jax.jit(rule.build(dict(m.statics)))
        rew_comp = rew_fn.lower(*rew_args).compile()
        unf = _unfused_cost(level, m)
        row = {"name": region_name(m), "rule": rule.name,
               "count": g["count"],
               "flops": unf["flops"], "bytes": unf["bytes"],
               "fused": _cost(base_comp),
               "ms": round(_slope_ms(base_comp, base_args, reps=reps), 4),
               "rewritten": {
                   **_cost(rew_comp),
                   "ms": round(_slope_ms(rew_comp, rew_args, reps=reps),
                               4)}}
        regions.append(row)

    # full-graph A/B: original vs rewritten program, same flat inputs
    from jax._src import core as jax_core
    res = rewrite_target(target, rules)
    flat_in = [jax.device_put(_seed_value(a, rng))
               for a in res.closed.in_avals]
    base_full = jax.jit(jax_core.jaxpr_as_fun(res.closed)) \
                   .lower(*flat_in).compile()
    rew_full = jax.jit(res.fn_flat).lower(*flat_in).compile()
    step_ms = _slope_ms(base_full, flat_in, reps=reps)
    step_ms_rew = _slope_ms(rew_full, flat_in, reps=reps)
    full = {"baseline": {**_cost(base_full),
                         "ms": round(step_ms, 4)},
            "rewritten": {**_cost(rew_full),
                          "ms": round(step_ms_rew, 4)},
            "note": ("whole-graph bytes already reflect XLA's own "
                     "elementwise fusion; the per-region sums measure "
                     "what the REWRITES fuse/delete")}
    b0, b1 = full["baseline"]["bytes"], full["rewritten"]["bytes"]
    full["bytes_ratio"] = round(b0 / b1, 4) if b1 else None

    for row in regions:
        row["pct_of_step"] = round(
            100.0 * row["count"] * row["ms"] / step_ms, 2) if step_ms \
            else None

    def _tot(sel, keys=("flops", "bytes", "ms")) -> Dict[str, float]:
        return {k: round(sum(sel(r).get(k, 0.0) * r["count"]
                             for r in regions), 4) for k in keys}

    tot_b = _tot(lambda r: r)
    tot_f = _tot(lambda r: r["fused"], keys=("flops", "bytes"))
    tot_r = _tot(lambda r: r["rewritten"])
    totals = {
        "baseline_per_op": tot_b,          # one kernel per jaxpr eqn
        "baseline_fused": tot_f,           # XLA gets the whole region
        "rewritten": tot_r,
        # the fusion claim: unfused-idiom traffic vs the substituted
        # fused call. baseline_fused/rewritten alongside shows how much
        # of it XLA's own fusion would also have recovered.
        "bytes_ratio_per_op": round(tot_b["bytes"] / tot_r["bytes"], 4)
        if tot_r["bytes"] else None,
        "bytes_ratio_fused": round(tot_f["bytes"] / tot_r["bytes"], 4)
        if tot_r["bytes"] else None,
        "ms_ratio": round(tot_b["ms"] / tot_r["ms"], 4)
        if tot_r["ms"] else None}

    regions.sort(key=lambda r: -(r["ms"] * r["count"]))
    return {"metric": f"resnet{depth}_per_region_profile",
            "mode": mode, "batch": batch, "image": image,
            "backend": jax.default_backend(),
            "step_ms": round(step_ms, 4),
            "step_ms_rewritten": round(step_ms_rew, 4),
            "fired": dict(res.fired),
            "regions": regions, "totals": totals, "full_graph": full}

"""Static Pallas kernel auditor: VMEM / grid / DMA / accumulator proofs.

The "static proof first, runtime check second" discipline (recompile
enumeration, guarded-by lint) extended to the kernel tree: every Pallas
kernel in ``paddle_tpu/ops/pallas/`` registers its entry points and
representative geometries (module attributes ``AUDIT_KIND``,
``AUDIT_CONFIG_KEYS``, ``AUDIT_GEOMETRIES``, ``AUDIT_WAIVERS`` and the
``audit_launches(geom, config)`` hook), and the auditor proves four
admissibility rules per (kernel, geometry, config) WITHOUT executing or
compiling anything — it traces the launch with ``jax.make_jaxpr`` and
reads the actual ``pallas_call`` equation (grid, BlockSpecs, index-map
jaxprs, scratch avals, kernel jaxpr), so the audited facts are the
kernel's own, not a hand-maintained mirror:

  KA001  VMEM footprint — pipelined BlockSpec blocks (x2 for Mosaic's
         double buffering) + VMEM ``scratch_shapes`` summed per grid
         step against the per-core budget (16 MiB hardware minus a
         2 MiB compiler reserve).
  KA002  grid coverage & index-map bounds — every index map evaluated
         over the FULL grid (scalar-prefetch operands included, via
         state-discharge of the index-map jaxpr): block starts must
         stay in bounds, and every output tile must be written with
         exact coverage — no unwritten tile, and revisits of an output
         block only in consecutive grid steps (the sequential-
         accumulation pattern; an interleaved revisit is a silent
         overwrite under Mosaic's change-triggered writeback).
  KA003  DMA discipline — walk of the kernel jaxpr (through cond /
         while / scan / pjit) proving every ``dma_start`` has a
         matching ``dma_wait`` keyed on (destination ref, semaphore
         ref) root identity — slot indices deliberately excluded so a
         double-buffered walk that starts slot (t+1)%2 while waiting
         slot t%2 keys correctly — and that no read of a DMA
         destination buffer precedes the first wait on it in program
         order.
  KA004  accumulator dtype — when a kernel takes bf16/f16/int8
         operands, its reduction carries must be f32: scratch
         accumulators (refs both read and compute-written), loop
         carries, sum-reductions, and int8 dots must not accumulate
         in a narrower type.

Findings ride the shared :class:`~paddle_tpu.analysis.framework.Finding`
schema. Waivers mirror the concurrency lint's noqa discipline: a
kernel module declares ``AUDIT_WAIVERS = ((rule, match, reason), ...)``
— a reasonless waiver is rejected at registration, suppressions are
inventoried in the report, and a waiver that suppresses nothing is
itself an error (stale waiver), so the clean-tree pin re-audits the
waiver set every run.

The autotune flywheel gates on this module: ``ops/autotune.record``
refuses an audit-failing winner (KA001/KA002), ``ops/autotune.lookup``
skips a stored winner whose geometry no longer passes, and
``tools/kernel_bench.py`` stamps every sweep row with its verdict.
"""
from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import core as jax_core

from .framework import Finding, Severity

# per-core VMEM: 16 MiB on every deployed TPU generation (v4/v5e/v5p),
# minus a reserve for Mosaic's own spills/stack — the audit budget a
# kernel's steady-state footprint must fit
VMEM_BYTES_PER_CORE = 16 * 2 ** 20
VMEM_COMPILER_RESERVE = 2 * 2 ** 20
VMEM_AUDIT_BUDGET = VMEM_BYTES_PER_CORE - VMEM_COMPILER_RESERVE

#: refuse to enumerate absurd grids rather than hang the lint
MAX_GRID_POINTS = 1 << 18

RULES = {
    "KA001": "VMEM footprint exceeds the per-core budget",
    "KA002": "index map out of bounds / output coverage not exact",
    "KA003": "DMA start without matching wait, or read before wait",
    "KA004": "low-precision reduction carry (accumulator must be f32)",
}

#: dtypes whose presence as kernel operands arms KA004
_LOW_PRECISION = {"bfloat16", "float16", "int8"}

#: the registered kernel modules (paddle_tpu.ops.pallas.<name>)
_KERNEL_MODULES = (
    "ragged_paged_attention",
    "flash_attention",
    "grouped_matmul",
    "int8_matmul",
    "conv_epilogue",
    "fused_norm_rope",
)

ALL_RULES = ("KA001", "KA002", "KA003", "KA004")


class KernelAuditError(Exception):
    """The audit itself could not run (trace failure, bad registration,
    unprovable scalar operand) — reported as an error, never silently
    passed."""


@dataclass(frozen=True)
class Waiver:
    rule: str
    match: str        # substring of the finding message (incl. kernel name)
    reason: str

    def __post_init__(self):
        if self.rule not in RULES:
            raise KernelAuditError(f"waiver for unknown rule {self.rule!r}")
        if not str(self.reason).strip():
            raise KernelAuditError(
                f"waiver {self.rule}({self.match!r}) needs a justification "
                f"reason, like every noqa in this tree")


@dataclass
class KernelSpec:
    """One registered kernel: its launch hook + audit metadata."""
    name: str
    kind: Optional[str]              # autotune store kind, or None
    config_keys: Tuple[str, ...]     # winner-dict keys record/lookup use
    geometries: Tuple[Dict[str, Any], ...]
    launches: Callable[..., Sequence]  # (geom, config) -> [(label, fn, args)]
    rules: Tuple[str, ...] = ALL_RULES
    waivers: Tuple[Waiver, ...] = ()
    geom_keys: Tuple[str, ...] = ()  # autotune geometry kwargs (sorted)


_REGISTRY: Optional[Dict[str, KernelSpec]] = None


def _build_registry() -> Dict[str, KernelSpec]:
    reg: Dict[str, KernelSpec] = {}
    for modname in _KERNEL_MODULES:
        mod = importlib.import_module(f"paddle_tpu.ops.pallas.{modname}")
        launches = getattr(mod, "audit_launches", None)
        geoms = getattr(mod, "AUDIT_GEOMETRIES", None)
        if launches is None or geoms is None:
            raise KernelAuditError(
                f"kernel module {modname} is not audit-registered: needs "
                f"AUDIT_GEOMETRIES + audit_launches(geom, config)")
        kind = getattr(mod, "AUDIT_KIND", None)
        waivers = tuple(Waiver(*w) for w in
                        getattr(mod, "AUDIT_WAIVERS", ()))
        geom_keys: Tuple[str, ...] = ()
        if kind is not None:
            geom_keys = tuple(sorted(getattr(mod, "AUDIT_GEOM_KEYS", ())))
            if not geom_keys:
                raise KernelAuditError(
                    f"{modname}: AUDIT_KIND={kind!r} needs AUDIT_GEOM_KEYS")
        reg[modname] = KernelSpec(
            name=modname, kind=kind,
            config_keys=tuple(getattr(mod, "AUDIT_CONFIG_KEYS", ())),
            geometries=tuple(dict(g) for g in geoms),
            launches=launches,
            rules=tuple(getattr(mod, "AUDIT_RULES", ALL_RULES)),
            waivers=waivers, geom_keys=geom_keys)
    return reg


def registry(refresh: bool = False) -> Dict[str, KernelSpec]:
    global _REGISTRY
    if _REGISTRY is None or refresh:
        _REGISTRY = _build_registry()
    return _REGISTRY


def kernel_signatures() -> Dict[str, Dict[str, Tuple[str, ...]]]:
    """``{autotune kind: {"geom_keys": (...), "config_keys": (...)}}``
    for every registered kernel with a persistent-store kind — the
    schema ``ops/autotune.py`` validates winners.json entries against."""
    out = {}
    for spec in registry().values():
        if spec.kind is not None:
            out[spec.kind] = {"geom_keys": spec.geom_keys,
                              "config_keys": spec.config_keys}
    return out


# ---------------------------------------------------------------------------
# trace extraction: find pallas_call eqns with concrete scalar operands
# ---------------------------------------------------------------------------

class _Unknown:
    __slots__ = ()

    def __repr__(self):
        return "<unknown>"


_UNKNOWN = _Unknown()

#: never eagerly materialize anything bigger than this during the
#: partial evaluation (scalar-prefetch metadata is tiny; tensors that
#: large are abstract by construction)
_MAX_EAGER_BYTES = 16 * 2 ** 20

#: higher-order primitives we recurse into rather than execute
_CALL_PRIMS = {"pjit", "closed_call", "core_call", "remat", "remat2",
               "custom_jvp_call", "custom_vjp_call",
               "custom_vjp_call_jaxpr"}


@dataclass
class ExtractedCall:
    eqn: Any                     # the pallas_call JaxprEqn
    scalar_values: List[Any]     # concrete scalar-prefetch operands (or
    #                            # _UNKNOWN where the trace lost them)


def _closed_jaxpr_param(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        cj = eqn.params.get(key)
        if cj is not None and hasattr(cj, "jaxpr"):
            return cj
    return None


def _partial_eval(closed, in_vals, calls):
    """Evaluate a jaxpr with a mix of concrete and _UNKNOWN inputs,
    executing only cheap known-input equations, recursing into call
    primitives, and recording every ``pallas_call`` with the concrete
    values of its invars (the scalar-prefetch operands are what KA002
    needs)."""
    jaxpr, consts = closed.jaxpr, closed.consts
    env: Dict[Any, Any] = {}

    def read(v):
        if isinstance(v, jax_core.Literal):
            return v.val
        return env.get(v, _UNKNOWN)

    def write(v, val):
        env[v] = val

    for cv, c in zip(jaxpr.constvars, consts):
        write(cv, c)
    for iv, val in zip(jaxpr.invars, in_vals):
        write(iv, val)

    for eqn in jaxpr.eqns:
        vals = [read(v) for v in eqn.invars]
        name = eqn.primitive.name
        if name == "pallas_call":
            calls.append(ExtractedCall(eqn=eqn, scalar_values=vals))
            outs = [_UNKNOWN] * len(eqn.outvars)
        elif name in _CALL_PRIMS:
            sub = _closed_jaxpr_param(eqn)
            if sub is not None and len(sub.jaxpr.invars) <= len(vals):
                # custom_* calls pass (consts..., args...); trailing
                # invars line up with trailing eqn invars
                outs = _partial_eval(
                    sub, vals[len(vals) - len(sub.jaxpr.invars):], calls)
            else:
                outs = [_UNKNOWN] * len(eqn.outvars)
        elif (all(v is not _UNKNOWN for v in vals)
              and name not in ("cond", "while", "scan")
              and all(_aval_bytes(ov.aval) <= _MAX_EAGER_BYTES
                      for ov in eqn.outvars)):
            try:
                res = eqn.primitive.bind(*vals, **eqn.params)
            except Exception:
                outs = [_UNKNOWN] * len(eqn.outvars)
            else:
                outs = list(res) if eqn.primitive.multiple_results else [res]
        else:
            outs = [_UNKNOWN] * len(eqn.outvars)
        for ov, val in zip(eqn.outvars, outs):
            write(ov, val)
    return [read(v) for v in jaxpr.outvars]


def _aval_bytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def extract_pallas_calls(fn, args) -> List[ExtractedCall]:
    """Trace ``fn(*args)`` (args may mix concrete arrays with
    ShapeDtypeStructs) and return every pallas_call equation with the
    concrete values reaching its invars.

    The partial evaluation runs under ``ensure_compile_time_eval`` so
    its eager binds stay concrete even when the audit is triggered
    inside an outer jit trace (autotune.lookup audits winners at trace
    time). The ``make_jaxpr`` trace itself must NOT — inside that
    context scalar closures materialise as captured-constant arrays,
    which pallas_call rejects."""
    closed = jax.make_jaxpr(fn)(*args)
    in_vals = []
    for a in jax.tree_util.tree_leaves(args):
        if isinstance(a, jax.ShapeDtypeStruct):
            in_vals.append(_UNKNOWN)
        else:
            in_vals.append(a)
    calls: List[ExtractedCall] = []
    with jax.ensure_compile_time_eval():
        _partial_eval(closed, in_vals, calls)
    if not calls:
        raise KernelAuditError("trace contains no pallas_call")
    return calls


# ---------------------------------------------------------------------------
# KA001 — VMEM footprint
# ---------------------------------------------------------------------------

def _block_dims(bm) -> Tuple[int, ...]:
    """Block shape with squeezed (Mapped) dims as 1."""
    return tuple(int(d) if isinstance(d, (int, np.integer)) else 1
                 for d in bm.block_shape)


def _block_memory_space(bm):
    return getattr(bm.transformed_block_aval, "memory_space", None)


def _is_pipelined_vmem(bm) -> bool:
    """True when the operand is windowed into VMEM by the pipeline (the
    default); ANY/SMEM operands stay in HBM/SMEM and cost no VMEM."""
    ms = _block_memory_space(bm)
    return ms is None or str(ms).lower() in ("vmem", "tpumemoryspace.vmem")


def vmem_footprint(call: ExtractedCall) -> Dict[str, Any]:
    """The per-grid-step VMEM bytes of one pallas_call: pipelined
    blocks (x2 — Mosaic double-buffers every windowed operand so the
    next block's copy overlaps compute) plus VMEM scratch (allocated
    once, not double-buffered)."""
    gm = call.eqn.params["grid_mapping"]
    blocks = []
    blocks_bytes = 0
    for bm in gm.block_mappings:
        nbytes = int(np.prod(_block_dims(bm))) * np.dtype(
            bm.array_shape_dtype.dtype).itemsize
        pipelined = _is_pipelined_vmem(bm)
        contrib = 2 * nbytes if pipelined else 0
        blocks_bytes += contrib
        blocks.append({"origin": str(bm.origin),
                       "block": list(_block_dims(bm)),
                       "dtype": str(bm.array_shape_dtype.dtype),
                       "bytes": nbytes, "pipelined": pipelined,
                       "vmem_bytes": contrib})
    scratch_bytes = 0
    sem_slots = 0
    kjaxpr = call.eqn.params["jaxpr"]
    n_lead = gm.num_index_operands + gm.num_inputs + gm.num_outputs
    for v in kjaxpr.invars[n_lead:]:
        aval = v.aval
        ms = str(getattr(aval, "memory_space", "")).lower()
        if "sem" in ms or "sem" in str(getattr(aval, "dtype", "")):
            sem_slots += int(np.prod(aval.shape)) if aval.shape else 1
        elif "smem" in ms:
            pass  # scalar scratch: SMEM, not VMEM
        else:
            scratch_bytes += (int(np.prod(aval.shape))
                              * np.dtype(aval.dtype).itemsize)
    return {"grid": [int(g) for g in gm.grid],
            "blocks": blocks,
            "blocks_bytes": int(blocks_bytes),
            "scratch_bytes": int(scratch_bytes),
            "sem_slots": int(sem_slots),
            "total_bytes": int(blocks_bytes + scratch_bytes),
            "budget_bytes": VMEM_AUDIT_BUDGET}


def _check_ka001(call: ExtractedCall, ctx: str, emit) -> Dict[str, Any]:
    fp = vmem_footprint(call)
    fp["ok"] = fp["total_bytes"] <= fp["budget_bytes"]
    if not fp["ok"]:
        emit("KA001",
             f"{ctx}: VMEM footprint {fp['total_bytes']} B "
             f"(blocks x2 {fp['blocks_bytes']} + scratch "
             f"{fp['scratch_bytes']}) exceeds budget "
             f"{fp['budget_bytes']} B")
    return fp


# ---------------------------------------------------------------------------
# KA002 — grid coverage & index-map bounds
# ---------------------------------------------------------------------------

def _grid_index_arrays(grid) -> List[np.ndarray]:
    """Flat row-major enumeration of the grid (last dim innermost —
    Pallas's iteration order), one int32 array per grid dim."""
    mesh = np.meshgrid(*[np.arange(g, dtype=np.int32) for g in grid],
                       indexing="ij")
    return [m.reshape(-1) for m in mesh]


def _discharged_index_map(bm):
    from jax._src.state.discharge import discharge_state
    cj = bm.index_map_jaxpr
    return discharge_state(cj.jaxpr, cj.consts)


def _eval_index_map(bm, grid, scalar_values, ctx: str) -> np.ndarray:
    """Evaluate one block's index map over the full grid. Returns
    ``[n_steps, n_block_dims]`` int64 block indices."""
    n_steps = int(np.prod(grid)) if grid else 1
    idx_arrays = _grid_index_arrays(grid)
    dj, consts = _discharged_index_map(bm)
    n_grid = len(grid)
    n_out = len(bm.block_shape)
    scalar_args = []
    for k, aval in enumerate(dj.invars[n_grid:]):
        val = (scalar_values[k] if k < len(scalar_values) else _UNKNOWN)
        if val is _UNKNOWN:
            # the map may not actually read this operand; zeros are
            # fine then — but if it does, the result would be wrong,
            # so require concreteness when the operand is used
            used = any(v is dj.invars[n_grid + k]
                       for eqn in dj.eqns for v in eqn.invars)
            if used:
                raise KernelAuditError(
                    f"{ctx}: index map reads scalar-prefetch operand "
                    f"#{k} but its value was not concrete at trace "
                    f"time — pass it as a concrete array in "
                    f"audit_launches")
            val = np.zeros(aval.aval.shape, np.dtype(aval.aval.dtype))
        scalar_args.append(np.asarray(val))
    if not dj.eqns:
        # fast path: pure pass-through maps (the common case) — outputs
        # are grid indices or literals, no tracing needed
        outs = []
        for ov in dj.outvars[:n_out]:
            if isinstance(ov, jax_core.Literal):
                outs.append(np.full(n_steps, int(ov.val), np.int64))
            else:
                pos = dj.invars.index(ov)
                outs.append(idx_arrays[pos].astype(np.int64))
        return np.stack(outs, axis=-1)

    def one(ij):
        res = jax_core.eval_jaxpr(dj, consts, *ij, *scalar_args)
        return [jnp.asarray(r, jnp.int32) for r in res[:n_out]]

    with jax.ensure_compile_time_eval():
        stacked = jax.vmap(one)(tuple(jnp.asarray(a) for a in idx_arrays))
    return np.stack([np.asarray(s, np.int64) for s in stacked], axis=-1)


def _check_ka002(call: ExtractedCall, ctx: str, emit) -> int:
    gm = call.eqn.params["grid_mapping"]
    grid = tuple(int(g) for g in gm.grid)
    n_steps = int(np.prod(grid)) if grid else 1
    if n_steps > MAX_GRID_POINTS:
        raise KernelAuditError(
            f"{ctx}: grid {grid} has {n_steps} steps > "
            f"{MAX_GRID_POINTS}; register a smaller representative "
            f"geometry")
    ndg = int(getattr(gm, "num_dynamic_grid_bounds", 0))
    scalars = call.scalar_values[ndg:ndg + gm.num_index_operands]
    checked = 0
    for bm in gm.block_mappings:
        origin = str(bm.origin)
        is_output = origin.startswith("output")
        if not _is_pipelined_vmem(bm) and not is_output:
            continue  # ANY-space: the kernel indexes it manually (DMA)
        bctx = f"{ctx} {origin}"
        idx = _eval_index_map(bm, grid, scalars, bctx)
        checked += 1
        bdims = np.array(_block_dims(bm), np.int64)
        adims = np.array(bm.array_shape_dtype.shape, np.int64)
        starts = idx * bdims
        bad_lo = starts < 0
        bad_hi = starts + bdims > adims
        if bad_lo.any() or bad_hi.any():
            step = int(np.argwhere((bad_lo | bad_hi).any(axis=1))[0][0])
            emit("KA002",
                 f"{bctx}: index map leaves bounds at grid step {step} "
                 f"(block index {idx[step].tolist()}, block "
                 f"{bdims.tolist()}, array {adims.tolist()})")
            continue
        if is_output:
            n_tiles_dim = -(-adims // bdims)  # ceil
            want = int(np.prod(n_tiles_dim))
            flat = np.ravel_multi_index(idx.T, n_tiles_dim)
            seen = np.unique(flat)
            if len(seen) != want:
                emit("KA002",
                     f"{bctx}: output coverage not exact — "
                     f"{len(seen)}/{want} tiles written (unwritten "
                     f"tiles would hold garbage)")
                continue
            # revisits must be consecutive in grid order: under the
            # change-triggered writeback, block (m,n) revisited at
            # non-adjacent steps is flushed then silently overwritten
            change = np.flatnonzero(np.diff(flat) != 0)
            n_runs = len(change) + 1
            if n_runs != want:
                first_bad = int(change[np.argmax(
                    np.diff(np.concatenate([[0], change])) >= 0)])
                emit("KA002",
                     f"{bctx}: output block revisited in non-"
                     f"consecutive grid steps ({n_runs} write runs for "
                     f"{want} tiles, e.g. around step {first_bad}) — "
                     f"interleaved revisits silently overwrite")
    return checked


# ---------------------------------------------------------------------------
# kernel-jaxpr walk shared by KA003 / KA004
# ---------------------------------------------------------------------------

@dataclass
class _KernelEvent:
    kind: str                    # dma_start | dma_wait | get | put | loop
    roots: Tuple[int, ...] = ()  # kernel invar indices of the ref args
    lits: Tuple = ()             # static literal operands (slot indices)
    aval: Any = None


def _walk_kernel(jaxpr, env, events: List[_KernelEvent]):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        roots = tuple(env[v] for v in eqn.invars
                      if not isinstance(v, jax_core.Literal) and v in env)
        lits = tuple(v.val for v in eqn.invars
                     if isinstance(v, jax_core.Literal)
                     and np.ndim(v.val) == 0)
        if name in ("dma_start", "dma_wait"):
            events.append(_KernelEvent(name, roots, lits))
        elif name == "get":
            events.append(_KernelEvent("get", roots, lits))
        elif name in ("swap", "addupdate", "masked_swap"):
            events.append(_KernelEvent("put", roots, lits))
        elif name in ("reduce_sum", "cumsum", "cumlogsumexp"):
            events.append(_KernelEvent(
                "reduce", (), (), eqn.invars[0].aval))
        elif name == "dot_general":
            events.append(_KernelEvent(
                "dot", (),
                (str(eqn.invars[0].aval.dtype),
                 str(eqn.invars[1].aval.dtype)),
                eqn.outvars[0].aval))
        subs = []
        if name == "cond":
            for br in eqn.params["branches"]:
                subs.append((br.jaxpr, list(eqn.invars[1:])))
        elif name == "while":
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            carry = list(eqn.invars[cn + bn:])
            events.append(_KernelEvent(
                "carry", (), (), [v.aval for v in carry]))
            cj, bj = eqn.params["cond_jaxpr"], eqn.params["body_jaxpr"]
            subs.append((cj.jaxpr, list(eqn.invars[:cn]) + carry))
            subs.append((bj.jaxpr, list(eqn.invars[cn:cn + bn]) + carry))
        elif name == "scan":
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            events.append(_KernelEvent(
                "carry", (), (),
                [v.aval for v in eqn.invars[nc:nc + ncar]]))
            subs.append((eqn.params["jaxpr"].jaxpr, list(eqn.invars)))
        else:
            sub = _closed_jaxpr_param(eqn)
            if sub is not None and len(sub.jaxpr.invars) <= len(eqn.invars):
                subs.append((sub.jaxpr,
                             list(eqn.invars)[-len(sub.jaxpr.invars):]))
        for sjaxpr, outer in subs:
            senv = {}
            for iv, ov in zip(sjaxpr.invars, outer):
                if (not isinstance(ov, jax_core.Literal)) and ov in env:
                    senv[iv] = env[ov]
            _walk_kernel(sjaxpr, senv, events)


def _kernel_events(call: ExtractedCall) -> List[_KernelEvent]:
    kjaxpr = call.eqn.params["jaxpr"]
    env = {v: i for i, v in enumerate(kjaxpr.invars)}
    events: List[_KernelEvent] = []
    _walk_kernel(kjaxpr, env, events)
    return events


def _ref_ranges(call: ExtractedCall):
    gm = call.eqn.params["grid_mapping"]
    n_idx = gm.num_index_operands
    n_in = gm.num_inputs
    n_out = gm.num_outputs
    n_total = len(call.eqn.params["jaxpr"].invars)
    return {"scalar": range(0, n_idx),
            "input": range(n_idx, n_idx + n_in),
            "output": range(n_idx + n_in, n_idx + n_in + n_out),
            "scratch": range(n_idx + n_in + n_out, n_total)}


# ---------------------------------------------------------------------------
# KA003 — DMA discipline
# ---------------------------------------------------------------------------

def _check_ka003(call: ExtractedCall, ctx: str, emit) -> int:
    events = _kernel_events(call)
    kjaxpr = call.eqn.params["jaxpr"]

    def dma_key(ev):
        # (dst root, sem root): a start and its wait bind the same
        # destination buffer and semaphore. Pairing is at buffer
        # granularity, not unrolled-slot granularity — double-buffered
        # kernels start slot (t+1)%2 and wait slot t%2 with traced
        # indices, which slot-exact keys would falsely flag.
        refs = [r for r in ev.roots
                if hasattr(kjaxpr.invars[r].aval, "memory_space")]
        return tuple(refs[1:]) if len(refs) >= 2 else tuple(refs)

    starts: Dict[Tuple, int] = {}
    waited: Dict[Tuple, int] = {}
    dst_roots = set()
    first_wait_pos: Dict[int, int] = {}
    n_pairs = 0
    for pos, ev in enumerate(events):
        key = dma_key(ev) if ev.kind in ("dma_start", "dma_wait") else None
        if ev.kind == "dma_start":
            n_pairs += 1
            starts[key] = starts.get(key, 0) + 1
            if key:
                dst_roots.add(key[0])
        elif ev.kind == "dma_wait":
            waited[key] = waited.get(key, 0) + 1
            if key:
                first_wait_pos.setdefault(key[0], pos)
    for key, n in starts.items():
        if waited.get(key, 0) == 0:
            emit("KA003",
                 f"{ctx}: dma_start on destination/semaphore "
                 f"{key} has no matching dma_wait — the copy may "
                 f"still be in flight when its buffer is read")
    # read-before-wait: the first get on a DMA destination must come
    # after some wait on that destination in program order
    for pos, ev in enumerate(events):
        if ev.kind == "get" and ev.roots and ev.roots[0] in dst_roots:
            root = ev.roots[0]
            w = first_wait_pos.get(root)
            if w is None or w > pos:
                aval = kjaxpr.invars[root].aval
                emit("KA003",
                     f"{ctx}: read of DMA destination buffer "
                     f"{aval} precedes any dma_wait on it")
            break
    return n_pairs


# ---------------------------------------------------------------------------
# KA004 — accumulator dtype
# ---------------------------------------------------------------------------

def _is_low_precision(dtype) -> bool:
    return str(np.dtype(dtype)) in _LOW_PRECISION


def _np_dtype(aval):
    """The aval's numpy dtype, or None for non-data types (semaphores
    carry a 'dma_sem' pseudo-dtype numpy cannot interpret)."""
    try:
        return np.dtype(getattr(aval, "dtype", None))
    except TypeError:
        return None


def _is_float(dt) -> bool:
    # jnp.issubdtype, not np: bf16 is an ml_dtypes extension type that
    # numpy does not classify under np.floating (operates on dtypes,
    # never on traced values)
    return jnp.issubdtype(dt, jnp.floating)


def _check_ka004(call: ExtractedCall, ctx: str, emit) -> int:
    gm = call.eqn.params["grid_mapping"]
    kjaxpr = call.eqn.params["jaxpr"]
    low = any(_is_low_precision(bm.array_shape_dtype.dtype)
              for bm in gm.block_mappings)
    if not low:
        return 0
    events = _kernel_events(call)
    ranges = _ref_ranges(call)
    got_get, got_put = set(), set()
    checks = 0
    for ev in events:
        if ev.kind == "get" and ev.roots:
            got_get.add(ev.roots[0])
        elif ev.kind == "put" and ev.roots:
            got_put.add(ev.roots[0])
    for root in ranges["scratch"]:
        aval = kjaxpr.invars[root].aval
        dt = _np_dtype(aval)
        if dt is None or not _is_float(dt):
            continue
        checks += 1
        if (root in got_get and root in got_put
                and dt.itemsize < 4):
            emit("KA004",
                 f"{ctx}: scratch accumulator {aval} is read-modify-"
                 f"written in {dt} — reduction carries must be f32 "
                 f"when kernel operands are bf16/int8")
    for ev in events:
        if ev.kind == "carry":
            for aval in ev.aval:
                dt = _np_dtype(aval)
                if dt is not None and _is_float(dt):
                    checks += 1
                    if dt.itemsize < 4:
                        emit("KA004",
                             f"{ctx}: loop carry {aval} accumulates in "
                             f"{dt} — flash/matmul carries must be f32")
        elif ev.kind == "reduce":
            dt = np.dtype(ev.aval.dtype)
            if _is_float(dt):
                checks += 1
                if dt.itemsize < 4:
                    emit("KA004",
                         f"{ctx}: sum-reduction over {dt} operand — "
                         f"softmax/reduction sums must run in f32")
        elif ev.kind == "dot":
            in_dts = ev.lits
            out_dt = np.dtype(ev.aval.dtype)
            if all(d == "int8" for d in in_dts):
                checks += 1
                if out_dt.itemsize < 4:
                    emit("KA004",
                         f"{ctx}: int8xint8 dot accumulates in "
                         f"{out_dt} — needs "
                         f"preferred_element_type=f32/int32")
    return checks


# ---------------------------------------------------------------------------
# per-launch / per-kernel drivers
# ---------------------------------------------------------------------------

_RULE_FNS = {"KA001": _check_ka001, "KA002": _check_ka002,
             "KA003": _check_ka003, "KA004": _check_ka004}


def audit_callable(kernel: str, label: str, fn, args,
                   rules: Sequence[str] = ALL_RULES,
                   waivers: Sequence[Waiver] = ()):
    """Audit one traceable launch. Returns ``(findings, suppressed,
    vmem_rows, rule_evals)`` — findings as :class:`Finding`, one vmem
    table row per pallas_call."""
    findings: List[Finding] = []
    suppressed: List[Dict[str, str]] = []
    vmem_rows: List[Dict[str, Any]] = []
    rule_evals = {r: 0 for r in ALL_RULES}

    def emitter(rule):
        def emit(r, message):
            for w in waivers:
                if w.rule == r and w.match in message:
                    suppressed.append({"rule": r, "message": message,
                                       "match": w.match,
                                       "reason": w.reason})
                    return
            findings.append(Finding(
                pass_name=f"kernel-audit/{r}", severity=Severity.ERROR,
                graph=f"{kernel}:{label}", message=message))
        return emit

    calls = extract_pallas_calls(fn, args)
    for ci, call in enumerate(calls):
        ctx = f"{kernel}:{label}" + (f"#call{ci}" if len(calls) > 1 else "")
        for rule in rules:
            res = _RULE_FNS[rule](call, ctx, emitter(rule))
            if rule == "KA001":
                row = dict(res)
                row.update({"kernel": kernel, "launch": label})
                vmem_rows.append(row)
                rule_evals[rule] += 1
            else:
                rule_evals[rule] += int(res)
    return findings, suppressed, vmem_rows, rule_evals


def _spec_launches(spec: KernelSpec, geom: Dict[str, Any],
                   config: Optional[Dict[str, Any]]):
    launches = spec.launches(dict(geom), dict(config) if config else None)
    if not launches:
        raise KernelAuditError(
            f"{spec.name}: audit_launches returned no launches for "
            f"{geom}")
    return launches


def audit_kernel(name: str, geom: Dict[str, Any],
                 config: Optional[Dict[str, Any]] = None,
                 rules: Optional[Sequence[str]] = None):
    """Audit one registered kernel at one geometry (and optional
    explicit winner config). Returns the same tuple as
    :func:`audit_callable`, aggregated over the geometry's launches."""
    spec = registry()[name]
    use_rules = tuple(rules) if rules is not None else spec.rules
    findings, suppressed, vmem, evals = [], [], [], \
        {r: 0 for r in ALL_RULES}
    for label, fn, args in _spec_launches(spec, geom, config):
        f, s, v, e = audit_callable(name, label, fn, args,
                                    rules=use_rules,
                                    waivers=spec.waivers)
        findings += f
        suppressed += s
        for row in v:
            row["geometry"] = dict(geom)
            if config:
                row["config"] = dict(config)
        vmem += v
        for r, n in e.items():
            evals[r] += n
    return findings, suppressed, vmem, evals


# the flywheel gate caches verdicts: autotune.lookup audits at most
# once per (kind, geometry, config) per process
_VERDICT_CACHE: Dict[Tuple, Dict[str, Any]] = {}

#: the admission rules a winner config must pass to be recorded or
#: applied — KA001/KA002 are config-dependent; KA003/KA004 are
#: properties of the kernel body, covered by the clean-tree pin
GATE_RULES = ("KA001", "KA002")


def audit_config(kind: str, geom: Dict[str, Any],
                 config: Optional[Dict[str, Any]],
                 use_cache: bool = True) -> Dict[str, Any]:
    """The flywheel admission verdict for one autotune winner:
    ``{"ok": bool, "rules": [rule, ...], "detail": str}``. Unknown
    kinds fail closed with rule ``unregistered``; a launch that cannot
    even trace fails with rule ``build``."""
    key = (kind, tuple(sorted((k, str(v)) for k, v in geom.items())),
           tuple(sorted((k, str(v)) for k, v in (config or {}).items())))
    if use_cache and key in _VERDICT_CACHE:
        return dict(_VERDICT_CACHE[key])
    spec = next((s for s in registry().values() if s.kind == kind), None)
    if spec is None:
        verdict = {"ok": False, "rules": ["unregistered"],
                   "detail": f"kind {kind!r} has no registered kernel"}
    else:
        try:
            findings, _, _, _ = audit_kernel(
                spec.name, geom, config, rules=GATE_RULES)
        except Exception as e:
            verdict = {"ok": False, "rules": ["build"],
                       "detail": f"{type(e).__name__}: {e}"}
        else:
            rules = sorted({f.pass_name.split("/")[-1] for f in findings})
            verdict = {"ok": not findings, "rules": rules,
                       "detail": "; ".join(f.message for f in findings[:2])}
    _VERDICT_CACHE[key] = dict(verdict)
    return verdict


def clear_verdict_cache():
    _VERDICT_CACHE.clear()


def _store_geometries(spec: KernelSpec):
    """Every geometry recorded for this kernel in the persistent
    autotune store (with its winner config) — the swept configs the
    flywheel would actually apply."""
    if spec.kind is None:
        return []
    import json

    from paddle_tpu.ops import autotune as at
    raw = at.raw_store()
    out = []
    for gkey, win in raw.get(spec.kind, {}).items():
        try:
            geom = json.loads(gkey)
        except ValueError:
            continue
        if isinstance(geom, dict) and isinstance(win, dict):
            out.append((geom, win))
    return out


def run_kernel_audit(include_store: bool = True) -> Dict[str, Any]:
    """The ``graph_lint --suite kernels`` entry: audit every registered
    kernel over its registered geometries (plus, when a persistent
    autotune store is configured, every swept geometry/winner in it).
    """
    findings: List[Finding] = []
    suppressed: List[Dict[str, str]] = []
    vmem: List[Dict[str, Any]] = []
    errors: List[str] = []
    rule_evals = {r: 0 for r in ALL_RULES}
    n_launches = 0
    try:
        reg = registry()
    except Exception as e:
        return {"ok": False, "kernels": [], "launches": 0, "vmem": [],
                "by_rule": {}, "rule_evals": rule_evals, "findings": [],
                "suppressed": [], "stale_waivers": [],
                "errors": [f"registry: {type(e).__name__}: {e}"]}
    for name, spec in reg.items():
        jobs = [(g, None) for g in spec.geometries]
        if include_store:
            try:
                jobs += _store_geometries(spec)
            except Exception as e:
                errors.append(f"{name}: store geometries unreadable: "
                              f"{type(e).__name__}: {e}")
        for geom, config in jobs:
            n_launches += 1
            try:
                f, s, v, e = audit_kernel(name, geom, config)
            except Exception as exc:
                errors.append(f"{name} @ {geom}: "
                              f"{type(exc).__name__}: {exc}")
                continue
            findings += f
            suppressed += s
            vmem += v
            for r, n in e.items():
                rule_evals[r] += n
    # stale-waiver discipline: a waiver that suppressed nothing across
    # the whole run is dead weight hiding a future regression
    stale = []
    used = {(s["rule"], s["match"]) for s in suppressed}
    for name, spec in reg.items():
        for w in spec.waivers:
            if (w.rule, w.match) not in used:
                stale.append({"kernel": name, "rule": w.rule,
                              "match": w.match, "reason": w.reason})
    by_rule = {r: 0 for r in ALL_RULES}
    for f in findings:
        by_rule[f.pass_name.split("/")[-1]] += 1
    return {
        "ok": not findings and not errors and not stale,
        "kernels": sorted(reg),
        "launches": n_launches,
        "vmem": vmem,
        "by_rule": by_rule,
        "rule_evals": rule_evals,
        "findings": [{"pass": f.pass_name, "severity": f.severity,
                      "graph": f.graph, "message": f.message}
                     for f in findings],
        "suppressed": suppressed,
        "stale_waivers": stale,
        "errors": errors,
    }

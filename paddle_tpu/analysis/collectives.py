"""Collective-consistency pass: pipeline stages must issue identical
collective sequences.

Generalizes ``Engine._verify_pp_forward_order`` (the ADVICE r5 guard):
that check proves the pp stage list matches the model's forward
*dataflow*; this one proves the stage *programs* agree on the one
thing that deadlocks or silently corrupts a pipeline — the ordered
sequence of collectives each stage issues. Two stages that disagree
(one psum where another ppermutes, different axes, different scan trip
counts around a collective) hang the mesh at best; at worst a
reordered pair of reductions completes with transposed data.

The signature of a program is the depth-first ordered list of its
collective equations with their semantics-bearing params (axis names,
permutation, tiling), each tagged with the loop structure that repeats
it (a ppermute inside a length-8 scan is eight issues, not one — two
stages with different trip counts are NOT consistent). Everything
shape-local is deliberately excluded: stages hold different weight
chunks and may differ freely in local math.

Use :func:`collective_signature` directly, or the pass over a group of
:class:`GraphTarget`\\ s that carry ``meta['stage_group']`` — targets
in one group must agree pairwise.
"""
from __future__ import annotations

from typing import Any, List, Sequence, Tuple

from .framework import (Finding, GraphTarget, LintPass, Severity,
                        register_pass)

__all__ = ["COLLECTIVE_PRIMS", "collective_signature",
           "CollectiveConsistencyPass", "check_stage_consistency",
           "collective_cost_bytes", "scan_trip_counts"]

COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "pgather", "pshuffle",
}

# eqn params that carry collective SEMANTICS (vs. local tiling detail)
_SIG_PARAMS = ("axes", "axis_name", "axis_index_groups", "perm",
               "all_gather_dimension", "scatter_dimension",
               "split_axis", "concat_axis", "tiled")


def _freeze(v: Any):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


def collective_signature(jaxpr, include_loops: bool = False
                         ) -> List[Tuple]:
    """Ordered (prim, loop_nest, params) for every collective in the
    program, depth-first — the stage's communication contract.
    ``loop_nest`` records the loop frames that repeat the collective,
    with scan trip counts: a ppermute inside a length-8 scan is eight
    issues, and a stage scanning 4 layers differs from one scanning 8
    even when the body matches.

    ``include_loops=True`` additionally records every loop frame itself
    as a ``("__loop__", nest, (("length", n),))`` entry — the mode the
    TRAINING stage check runs in: pipeline stage chunks under GSPMD
    carry no explicit collectives (XLA inserts them at compile), but
    their layer-scan trip counts ARE the per-stage work contract, and a
    chunk scanning a different layer count desynchronizes the lockstep
    schedule exactly like a diverging collective would."""
    from ..core.graph_trace import sub_jaxprs
    from jax._src import core as jax_core

    sig: List[Tuple] = []

    def walk(j, loops: Tuple):
        if isinstance(j, jax_core.ClosedJaxpr):
            j = j.jaxpr
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                params = tuple(
                    (k, _freeze(eqn.params[k])) for k in _SIG_PARAMS
                    if k in eqn.params)
                sig.append((name, loops, params))
            for label, sub in sub_jaxprs(eqn):
                if name in ("scan", "while", "fori_loop"):
                    frame = (name, eqn.params.get("length"))
                    if include_loops:
                        sig.append(("__loop__", loops,
                                    (("length",
                                      eqn.params.get("length")),)))
                    walk(sub, loops + (frame,))
                else:
                    walk(sub, loops)
        return sig

    return walk(jaxpr, ())


#: wire-traffic weight per collective primitive: how many times the
#: payload crosses a link relative to its size (ring all-reduce moves
#: ~2(n-1)/n ≈ 2 payloads, a permute moves 1, gather/scatter families
#: ~1). Deliberately topology-free — the planner's comms term is a
#: RANKING proxy, not a wall-clock model.
_COLLECTIVE_WIRE_FACTOR = {
    "psum": 2.0, "psum2": 2.0, "pmax": 2.0, "pmin": 2.0, "pmean": 2.0,
    "ppermute": 1.0, "pbroadcast": 1.0, "all_gather": 1.0,
    "all_to_all": 1.0, "reduce_scatter": 1.0, "psum_scatter": 1.0,
    "pgather": 1.0, "pshuffle": 1.0,
}


def collective_cost_bytes(jaxpr) -> int:
    """Wire bytes the program's EXPLICIT collectives move, scan trip
    counts included: each collective contributes (output bytes) x
    (enclosing scan trips) x (per-prim wire factor). This prices what
    the trace can see — shard_map programs (the async pipeline
    schedules' per-tick ppermute pair) and manual psums; collectives
    GSPMD inserts at compile time are invisible here and the planner
    adds them analytically from the declared specs. A ``while`` body
    has no static trip count, so its collectives count once (a lower
    bound, stated rather than guessed). One number per graph so the
    planner's comms term and a test can pin it."""
    from ..core.graph_trace import sub_jaxprs
    from jax._src import core as jax_core
    from .framework import aval_nbytes

    total = 0.0

    def walk(j, mult: int):
        nonlocal total
        if isinstance(j, jax_core.ClosedJaxpr):
            j = j.jaxpr
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                out_b = sum(aval_nbytes(o.aval) for o in eqn.outvars)
                total += (out_b * mult
                          * _COLLECTIVE_WIRE_FACTOR.get(name, 1.0))
            for _label, sub in sub_jaxprs(eqn):
                trips = (eqn.params.get("length") if name == "scan"
                         else None)
                walk(sub, mult * int(trips) if trips is not None
                     else mult)

    walk(jaxpr, 1)
    return int(total)


def scan_trip_counts(jaxpr) -> List[int]:
    """Every ``lax.scan`` trip count in the program, depth-first."""
    from ..core.graph_trace import iter_jaxpr_eqns
    out = []
    for _path, eqn in iter_jaxpr_eqns(jaxpr):
        if (eqn.primitive.name == "scan"
                and eqn.params.get("length") is not None):
            out.append(int(eqn.params["length"]))
    return out


def check_stage_consistency(
        stages: Sequence[Tuple[str, Any]],
        include_loops: bool = False) -> List[Tuple[str, str]]:
    """Compare collective signatures across ``(name, jaxpr)`` stages.
    Returns [(stage_name, description)] for every stage diverging from
    the first one (the reference stage)."""
    if len(stages) < 2:
        return []
    ref_name, ref_jaxpr = stages[0]
    ref_sig = collective_signature(ref_jaxpr, include_loops)
    out = []
    for name, jaxpr in stages[1:]:
        sig = collective_signature(jaxpr, include_loops)
        if sig == ref_sig:
            continue
        # locate the first divergence for an actionable message
        i = 0
        while i < min(len(sig), len(ref_sig)) and sig[i] == ref_sig[i]:
            i += 1
        ours = sig[i] if i < len(sig) else "<end>"
        theirs = ref_sig[i] if i < len(ref_sig) else "<end>"
        out.append((name,
                    f"collective #{i} is {ours} but stage "
                    f"'{ref_name}' issues {theirs} "
                    f"({len(sig)} vs {len(ref_sig)} collectives total)"))
    return out


@register_pass
class CollectiveConsistencyPass(LintPass):
    """Group targets by ``meta['stage_group']`` and require identical
    collective signatures inside each group (loop trip counts included
    when any member sets ``meta['signature_include_loops']`` — the
    training stage-chunk mode). Run via :func:`framework.run_passes`
    this fires once per target but keeps state, reporting each group
    exactly once (on its last member).

    Per-target rule: a target carrying ``meta['expected_scan_trips']``
    (the 1F1B train step: ``pipeline_1f1b.schedule_ticks(S, M, V)``)
    must contain a scan with exactly that trip count — the schedule's
    fill + steady + drain tick arithmetic. A schedule edit that changes
    the tick count without updating ``schedule_ticks`` (or vice versa)
    is a lockstep desync and fails here before it ever runs."""

    name = "collective-consistency"

    def __init__(self):
        self._groups = {}

    def run(self, target: GraphTarget) -> List[Finding]:
        findings: List[Finding] = []
        expected = target.meta.get("expected_scan_trips")
        if expected is not None:
            trips = scan_trip_counts(target.jaxpr)
            if int(expected) not in trips:
                findings.append(self.finding(
                    target,
                    f"no scan with the schedule's expected trip count "
                    f"{expected} (traced scan lengths: {sorted(set(trips))})"
                    f" — the 1F1B tick arithmetic and the traced "
                    f"schedule disagree"))

        group = target.meta.get("stage_group")
        if group is None:
            return findings
        members = self._groups.setdefault(group, [])
        members.append((target.name, target.jaxpr,
                        bool(target.meta.get("signature_include_loops"))))
        total = target.meta.get("stage_count")
        if total is None or len(members) < total:
            return findings
        include_loops = any(m[2] for m in members)
        for name, desc in check_stage_consistency(
                [(n, j) for n, j, _ in members], include_loops):
            findings.append(Finding(
                pass_name=self.name, severity=Severity.ERROR,
                graph=name,
                message=f"pipeline stage group '{group}': {desc}"))
        return findings

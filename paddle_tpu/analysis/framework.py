"""Pass framework for the static-analysis subsystem.

Reference capability: the reference ships IR-level passes and runtime
enforcement (``paddle/pir`` pass infrastructure, ``phi/core/enforce.h``
check macros). The JAX-native counterpart analyses **jaxprs** — the
one IR every flagship program already lowers through — plus host-side
serving state (``kv_invariants.py``). This module is the shared
plumbing: a finding record, a pass protocol, and a report that the
``tools/graph_lint.py`` CLI and the tests consume identically.

A pass is a callable object with ``name`` / ``run(target) ->
List[Finding]``. Targets are :class:`GraphTarget` records (a traced
jaxpr plus the metadata passes need: declared compute dtype, which
outputs the caller donates/rebinds, how many batch slots the program
serves). Passes never run the program — everything here is tracing
plus host-side walks, so linting the flagship serving graphs costs
milliseconds, not XLA compiles.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Severity", "Finding", "GraphTarget", "LintPass",
           "LintReport", "PASS_REGISTRY", "register_pass",
           "default_passes", "run_passes", "trace_graph",
           "ExactnessContract", "RewritePass", "REWRITE_REGISTRY",
           "register_rewrite", "default_rewrites", "aval_nbytes"]


def aval_nbytes(aval) -> int:
    """Bytes of one abstract value (0 for token/effect avals without a
    dtype) — the ONE byte-accounting helper every pass uses (hbm peak,
    donation audit, sharding lint, planner cost model), so the passes
    cannot disagree on what a buffer weighs."""
    import numpy as np
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    n = int(np.prod(shape)) if shape else 1
    return n * np.dtype(dtype).itemsize

#: name -> LintPass subclass; every pass registers itself here so the
#: CLI (tools/graph_lint.py) and the tests build the same pass set —
#: a pass that exists but is wired nowhere is the vacuous-pass
#: anti-pattern in a new costume.
PASS_REGISTRY: Dict[str, type] = {}

#: name -> RewritePass subclass. Same contract as PASS_REGISTRY: the
#: rewrite suite (tools/graph_lint.py --suite rewrite), the rewriting
#: engine wrapper (serving) and the tests all build from this one
#: registry, so a rewrite that exists but is wired nowhere cannot
#: happen.
REWRITE_REGISTRY: Dict[str, type] = {}


def register_pass(cls):
    """Class decorator: add a LintPass subclass to ``PASS_REGISTRY``
    under its ``name``."""
    PASS_REGISTRY[cls.name] = cls
    return cls


def register_rewrite(cls):
    """Class decorator: add a RewritePass subclass to
    ``REWRITE_REGISTRY`` under its ``name``."""
    REWRITE_REGISTRY[cls.name] = cls
    return cls


def default_rewrites(names=None) -> List["RewritePass"]:
    """One instance of every registered rewrite (or of ``names``),
    ordered by ``priority`` (stable: registration order breaks ties).
    The rewriter hands each anchor to the FIRST rule that matches it,
    so bigger-subgraph passes (the decode tail swallows an rms-norm;
    the conv epilogue swallows a layout-normalizable conv) must sort
    ahead of the smaller passes they contain."""
    if names is None:
        rules = [cls() for cls in REWRITE_REGISTRY.values()]
    else:
        rules = [REWRITE_REGISTRY[n]() for n in names]
    return sorted(rules, key=lambda r: r.priority)


def default_passes(**ctor_kwargs) -> List["LintPass"]:
    """One instance of every registered pass, in registration order.
    ``ctor_kwargs[name]`` supplies per-pass constructor kwargs (e.g.
    ``{"recompile-hazard": {"limit": 16}})``."""
    return [cls(**ctor_kwargs.get(name, {}))
            for name, cls in PASS_REGISTRY.items()]


class Severity:
    ERROR = "error"      # invariant violated / silent-wrongness class
    WARNING = "warning"  # perf hazard, suspicious but not provably wrong
    INFO = "info"        # informational (counts, program inventories)

    ORDER = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclass
class Finding:
    """One lint result: which pass, on which graph, what and where."""
    pass_name: str
    severity: str
    graph: str                 # target name (e.g. "llama.serving_decode_block")
    message: str
    #: control-flow path to the offending eqn, e.g. (("scan","jaxpr"),)
    path: Tuple = ()

    def __str__(self) -> str:
        loc = "/".join(p[0] for p in self.path) or "top"
        return (f"[{self.severity}] {self.pass_name} @ {self.graph} "
                f"({loc}): {self.message}")


@dataclass
class GraphTarget:
    """A traced program plus the call-site facts passes need.

    ``donated_outputs``: indices into the jaxpr's flat outputs that the
    caller donates back in (pool arrays the engine rebinds) — they
    never cross to the host, so host-sync accounting excludes them.
    ``slots``: batch width of the program (decode-batch S), for
    per-slot byte budgets. ``steps_per_call``: decode steps one call
    advances (the fused block's k); host-pull budgets are per step.
    ``in_decode_loop``: the program IS a per-tick decode body — the
    host-sync pass applies its output-size budget only there.
    """
    name: str
    jaxpr: Any                              # jax.core.ClosedJaxpr
    compute_dtype: Any = None               # declared model dtype
    donated_outputs: Tuple[int, ...] = ()
    slots: int = 1
    steps_per_call: int = 1
    in_decode_loop: bool = False
    meta: Dict[str, Any] = field(default_factory=dict)


def trace_graph(name: str, fn: Callable, args: Sequence,
                static_kwargs: Optional[Dict[str, Any]] = None,
                **target_kw) -> GraphTarget:
    """Trace ``fn(*args, **static_kwargs)`` to a :class:`GraphTarget`.
    ``args`` may be ShapeDtypeStructs — tracing is abstract, nothing
    executes."""
    import jax
    closed = jax.make_jaxpr(
        lambda *a: fn(*a, **(static_kwargs or {})))(*args)
    return GraphTarget(name=name, jaxpr=closed, **target_kw)


class LintPass:
    """Base class: subclasses set ``name`` and implement ``run``."""

    name: str = "pass"

    def run(self, target: GraphTarget) -> List[Finding]:
        raise NotImplementedError

    def finding(self, target: GraphTarget, message: str,
                severity: str = Severity.ERROR,
                path: Tuple = ()) -> Finding:
        return Finding(pass_name=self.name, severity=severity,
                       graph=target.name, message=message, path=path)


@dataclass
class ExactnessContract:
    """What a rewrite is allowed to change about the numbers.

    ``bitwise=True`` — the replacement is byte-identical (integer
    outputs, or a substitution proven to round identically).
    ``ulp=N`` — the replacement performs the same operations in the
    same association, but compiler clustering (FMA contraction, fusion
    boundaries) may round differently: outputs must be within N units-
    in-last-place of the OUTPUT dtype (the kernel-substitution
    contract). Otherwise the rewrite genuinely reassociates (e.g.
    moving a dequant scale across a matmul) and must pin
    ``rtol``/``atol``: close-enough-by-accident is not a contract.
    """
    bitwise: bool = False
    ulp: int = 0
    rtol: float = 0.0
    atol: float = 0.0

    def describe(self) -> str:
        if self.bitwise:
            return "bitwise"
        if self.ulp:
            return f"ulp<={self.ulp}"
        return f"rtol={self.rtol:g} atol={self.atol:g}"


class RewritePass:
    """Base class for graph rewrites (the optimizer counterpart of
    :class:`LintPass`). Subclasses declare:

    * ``name`` — registry key;
    * ``contract`` — the :class:`ExactnessContract` the verifier
      enforces before the rewrite is allowed to ship;
    * ``patterns()`` — anchor-variant list of :mod:`patterns` trees
      describing the subgraph to replace;
    * ``arg_names`` — which pattern captures feed the replacement, in
      call order;
    * ``build(statics)`` — the replacement callable taking the captured
      values; ``statics`` holds the ``Lit`` captures (Python numbers).
    * ``validate(match, jaxpr)`` — optional cross-binding check.

    The machinery that applies these lives in ``analysis/rewrite.py``;
    passes themselves stay declarative.
    """

    name: str = "rewrite"
    contract: ExactnessContract = ExactnessContract(bitwise=True)
    arg_names: Tuple[str, ...] = ()
    #: rule order handed to the rewriter — lower runs first; passes
    #: whose pattern CONTAINS another pass's pattern must sort lower
    #: (see :func:`default_rewrites`)
    priority: int = 100

    def patterns(self):
        raise NotImplementedError

    def build(self, statics: Dict[str, Any]) -> Callable:
        raise NotImplementedError

    def validate(self, match, jaxpr) -> bool:
        return True


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)
    #: (pass, graph) pairs that ran — a pass that never ran is not a
    #: clean pass (the vacuous-pass lesson, ADVICE r5)
    ran: List[Tuple[str, str]] = field(default_factory=list)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings
                if f.severity == Severity.ERROR]

    @property
    def ok(self) -> bool:
        return not self.errors

    def extend(self, other: "LintReport") -> None:
        self.findings.extend(other.findings)
        self.ran.extend(other.ran)

    def summary(self) -> str:
        n_err = len(self.errors)
        n_warn = sum(f.severity == Severity.WARNING for f in self.findings)
        return (f"{len(self.ran)} pass runs, {n_err} errors, "
                f"{n_warn} warnings")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "runs": len(self.ran),
            "findings": [
                {"pass": f.pass_name, "severity": f.severity,
                 "graph": f.graph, "message": f.message,
                 "path": ["/".join(p) for p in f.path]}
                for f in sorted(
                    self.findings,
                    key=lambda f: Severity.ORDER.get(f.severity, 9))],
        }


def run_passes(passes: Sequence[LintPass],
               targets: Sequence[GraphTarget]) -> LintReport:
    """Run every pass over every target; findings are accumulated, a
    pass raising is converted into an ERROR finding (a crashed linter
    must never read as a clean one)."""
    report = LintReport()
    for target in targets:
        for p in passes:
            try:
                found = p.run(target)
            except Exception as e:  # noqa: BLE001 - surfaced as finding
                found = [Finding(
                    pass_name=p.name, severity=Severity.ERROR,
                    graph=target.name,
                    message=f"pass crashed: {type(e).__name__}: {e}")]
            report.findings.extend(found)
            report.ran.append((p.name, target.name))
    return report

"""Donation/aliasing audit: params and optimizer state enter the train
step donated, or the step pays double residency.

A training step that does not donate its state holds params + optimizer
moments TWICE at the update (old buffers pinned as live jit inputs
while the new ones materialize) — on a memory-bound trainer that is the
difference between fitting and OOMing, and like the host-sync logits
pull it produces zero errors and perfectly correct numerics. The audit
pins the invariant statically, the same way PR 4's host-sync byte
budget pinned the decode-output class:

* **undonated-state** (error): a param/optimizer-state input leaf not
  donated and larger than the per-leaf byte budget (default 256 B —
  scalars and step counters are free, weight-shaped leaves are not).
* **unaliasable-donation** (warning): a donated input with no output of
  identical shape/dtype to alias onto — XLA quietly drops the donation
  and the buffer is doubly resident anyway (the classic cause: a dtype
  or layout change on the updated state).
* an INFO inventory (donated vs pulled bytes) so the CLI shows what a
  step actually keeps on device vs returns to the host.

``jit_donation_flags`` extracts ground truth from a *lowering* (still
zero compiles): which flat inputs the jitted callable actually marks
``tf.aliasing_output``. The training targets declare donation flags in
meta (mirroring ``donate_argnums``); a test pins the two against each
other so the declared flags cannot drift from what jax really does —
the engine_geometry()-vs-live-engine lesson applied to donation.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from .framework import (Finding, GraphTarget, LintPass, Severity,
                        aval_nbytes as _nbytes, register_pass)

__all__ = ["DonationAuditPass", "jit_donation_flags"]


@register_pass
class DonationAuditPass(LintPass):
    name = "donation-audit"

    def __init__(self, max_undonated_bytes: int = 256):
        #: per-leaf budget for non-donated param/opt inputs
        self.max_bytes = int(max_undonated_bytes)

    def run(self, target: GraphTarget) -> List[Finding]:
        donated = target.meta.get("donated_invars")
        if donated is None:
            return []  # target declares no donation contract
        jaxpr = target.jaxpr.jaxpr
        if len(donated) != len(jaxpr.invars):
            return [self.finding(
                target,
                f"donated_invars has {len(donated)} flags for "
                f"{len(jaxpr.invars)} traced invars — the donation meta "
                f"is misaligned with the graph (unused args pruned from "
                f"a lowering?); fix the target construction")]
        labels = target.meta.get("invar_labels",
                                 [f"arg{i}" for i in
                                  range(len(jaxpr.invars))])
        classes = target.meta.get("invar_classes",
                                  ["?"] * len(jaxpr.invars))
        findings: List[Finding] = []

        don_bytes = pull_bytes = 0
        out_shapes = {}
        for o in jaxpr.outvars:
            aval = getattr(o, "aval", None)
            if aval is not None and getattr(aval, "shape", None) is not None:
                key = (tuple(aval.shape), np.dtype(aval.dtype).name)
                out_shapes[key] = out_shapes.get(key, 0) + 1

        for i, v in enumerate(jaxpr.invars):
            b = _nbytes(v.aval)
            if donated[i]:
                don_bytes += b
                key = (tuple(v.aval.shape), np.dtype(v.aval.dtype).name)
                if out_shapes.get(key, 0) > 0:
                    out_shapes[key] -= 1
                else:
                    findings.append(self.finding(
                        target,
                        f"{labels[i]} is donated but no output matches "
                        f"its shape/dtype {key} — XLA cannot alias it, "
                        f"the buffer is doubly resident anyway",
                        severity=Severity.WARNING))
            else:
                pull_bytes += b
                if classes[i] in ("param", "opt") and b > self.max_bytes:
                    findings.append(self.finding(
                        target,
                        f"{labels[i]} ({classes[i]}, {b} bytes) enters "
                        f"the step NON-donated (budget {self.max_bytes} "
                        f"B/leaf) — old and new buffers are live "
                        f"simultaneously at the update; add it to "
                        f"donate_argnums"))
        findings.append(self.finding(
            target,
            f"donation inventory: {don_bytes / 2**20:.2f} MiB donated "
            f"(updated in place), {pull_bytes / 2**20:.2f} MiB "
            f"non-donated inputs", severity=Severity.INFO))
        return findings


def jit_donation_flags(jitted, *args, n_invars: Optional[int] = None,
                       **kwargs) -> Sequence[bool]:
    """Which flat inputs of ``jitted`` are donation-aliased, from its
    LOWERED module (tracing only, no compile): jax stamps donated
    parameters with ``tf.aliasing_output`` (or ``jax.buffer_donor``) in
    the StableHLO entry function. ``args`` may be ShapeDtypeStructs."""
    lowered = jitted.lower(*args, **kwargs)
    text = lowered.as_text()
    # only the entry function's signature (one printed line); each
    # %argN's attribute dict sits between its marker and the next —
    # split on the markers rather than regex-matching the dict, whose
    # values legally contain nested braces ('{replicated}' shardings)
    head = next((ln for ln in text.splitlines() if "@main" in ln), text)
    parts = re.split(r"%arg(\d+):", head)
    flagged = set()
    arity = 0
    for idx_s, seg in zip(parts[1::2], parts[2::2]):
        arity = max(arity, int(idx_s) + 1)
        # the result list follows the last arg: stop at the arrow so a
        # result attribute can never be credited to that arg
        seg = seg.split("->")[0]
        if "tf.aliasing_output" in seg or "jax.buffer_donor" in seg:
            flagged.add(int(idx_s))
    # jit's default keep_unused=False PRUNES unused flat args from the
    # lowered @main: %argN numbers positions in the KEPT list, not the
    # caller's flat signature. Map back through kept_var_idx so the
    # flags align with an UNPRUNED jaxpr's invars (a step with one
    # unused state leaf would otherwise shift every flag after it).
    try:
        kept = sorted(lowered._lowering.compile_args["kept_var_idx"])
    except Exception:
        kept = None
    if kept is not None and arity == len(kept):
        flagged = {kept[i] for i in flagged}
        arity = kept[-1] + 1 if kept else 0
    if n_invars is None:
        try:
            import jax
            n_invars = len(jax.tree_util.tree_leaves(lowered.args_info))
        except Exception:
            n_invars = arity
    return [i in flagged for i in range(n_invars)]

"""Host-sync lint pass: device→host traffic in tick/decode loops.

The bug class PR 2's in-graph sampling fixed: an all-greedy decode
tick used to pull ``[S, V]`` f32 logits to the host every step (V·4
bytes per slot per step through the tunnelled runtime) when the step
only needed ``[S, 1]`` i32 tokens — a 1000x host-transfer tax that no
test catches because the tokens are still correct. Two statically
checkable symptoms:

* **callbacks** (error): ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` equations anywhere in a decode-loop graph. A
  callback inside the per-tick program is a host round-trip per step
  (and under ``lax.scan`` it serializes the whole loop on the host).
  Outside decode loops callbacks are reported as warnings — legal, but
  worth eyes.
* **oversized host pull** (error): the program's non-donated outputs —
  what the host actually fetches per call — exceed a per-slot,
  per-step byte budget. The engine donates and rebinds the KV pools,
  so the real pull is everything else; a ``[S, V]`` f32 logits output
  blows the default 64-byte budget ~1000x while the fused block's
  ``[S, k]`` i32 tokens cost 4.

The output-size rule only applies to targets marked
``in_decode_loop`` — prefill programs legitimately return logits once
per prompt, and charging them a per-step budget would be noise.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..core.graph_trace import iter_jaxpr_eqns
from .framework import (Finding, GraphTarget, LintPass, Severity,
                        register_pass)

__all__ = ["HostSyncPass"]

_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "callback")
_LOOP_PRIMS = {"scan", "while", "fori_loop"}


def _in_loop(path) -> bool:
    return any(frame[0] in _LOOP_PRIMS for frame in path)


@register_pass
class HostSyncPass(LintPass):
    name = "host-sync"

    def __init__(self, max_bytes_per_slot_step: int = 64):
        self.max_bytes = int(max_bytes_per_slot_step)

    def run(self, target: GraphTarget) -> List[Finding]:
        findings: List[Finding] = []
        closed = target.jaxpr

        # ---- callback scan ------------------------------------------
        for path, eqn in iter_jaxpr_eqns(closed):
            prim = eqn.primitive.name
            if not any(prim == c or prim.endswith("_callback")
                       for c in _CALLBACK_PRIMS):
                continue
            in_loop = _in_loop(path)
            hot = target.in_decode_loop or in_loop
            where = "inside a traced loop body" if in_loop \
                else "in the program"
            findings.append(self.finding(
                target,
                f"host callback `{prim}` {where} — every execution is "
                f"a device→host round-trip"
                + (" serializing the decode loop" if hot else ""),
                severity=Severity.ERROR if hot else Severity.WARNING,
                path=path))

        # ---- host-pull budget (decode-loop programs only) -----------
        if target.in_decode_loop:
            pulled = 0
            shapes = []
            for i, v in enumerate(closed.jaxpr.outvars):
                if i in target.donated_outputs:
                    continue  # donated & rebound: never crosses to host
                aval = v.aval
                n = int(np.prod(aval.shape)) if aval.shape else 1
                pulled += n * np.dtype(aval.dtype).itemsize
                shapes.append(f"{aval.dtype}{list(aval.shape)}")
            slots = max(target.slots, 1)
            steps = max(target.steps_per_call, 1)
            per = pulled / (slots * steps)
            if per > self.max_bytes:
                findings.append(self.finding(
                    target,
                    f"decode tick pulls {per:.0f} bytes/slot/step to "
                    f"the host (outputs {', '.join(shapes)}; budget "
                    f"{self.max_bytes}) — move the reduction (sampling/"
                    f"argmax) in-graph so only tokens cross"))
        return findings

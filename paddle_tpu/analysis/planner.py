"""Auto-parallel planner: search, rank, and trace-verify parallel plans.

The reference fleet picks a ``distributed_strategy`` for the user; here
(until this module) a human still hand-picked (dp, tp, pp, V, M,
schedule, zero stage, dtype) even though every ingredient of a cost
model already exists as a static pass. This module closes ROADMAP item
4's loop by COMPOSING them into one decision procedure:

1. **Enumerate** the legal configuration space for a device count:
   mesh factorizations (dp, tp, pp) x virtual chunks x microbatches x
   schedule x zero stage x dtype, pruned by the SAME legality the
   executors enforce — divisibility (layers per stage chunk, heads per
   tp shard, batch per microbatch per dp shard), the schedule table
   (``parallel.pipeline_async.schedule_legality``: ZB's V=1,
   interleaved M % S — the old dp=tp=1 restriction on
   ``1f1b_async``/``zb`` fell in r19 when the executors composed
   dp/tp into the shard_map, which widened this search automatically),
   and zero-stage applicability (needs dp > 1). Every pruned search
   branch is counted by reason — the search space is auditable, not
   implicit.

2. **Price** each legal point with a composed cost model:

   * *HBM peak* — ``estimate_hbm_peak`` over an abstract
     ``build_train_target`` trace of the point's real train step
     (zero compiles). Tracing happens at small proxy batches; when the
     requested batch is larger the peak is extrapolated through two
     proxy points (peak is affine in batch rows once the fixed
     state — params + optimizer moments — is in place), which is what
     makes the verification contract (below) a real check rather than
     the estimator agreeing with itself.
   * *step time* — a roofline proxy: per-device flops/bytes from ONE
     compiled single-device reference step per dtype
     (``hbm.xla_cost_analysis``; closed-form fallback when the backend
     omits the counters), scaled by the point's shard denominators,
     multiplied by the schedule's work factor (zb's residual-ring W
     is 4.5/4 since r19 — ``SCHEDULE_INFO``), and divided by
     ``schedule_efficiency(pp, M, V)``.
   * *comms* — explicit collectives priced from the trace
     (``collectives.collective_cost_bytes``: the async schedules'
     per-tick ppermute pairs, trip counts included) plus analytic
     terms for what GSPMD inserts at compile time and the trace cannot
     see: the dp gradient all-reduce, tp activation all-reduces, and
     the ZeRO-3 parameter regather.

   The rates (``CostModel``) are RANKING weights with TPU-ish
   magnitudes, not a wall-clock simulator — docs/ANALYSIS.md states
   the terms and their assumptions.

3. **Verify** the winner instead of trusting it: trace the winning
   point at the FULL requested batch and run the complete registered
   pass stack over it (hbm-peak with the budget, sharding-lint,
   donation-audit, collective-consistency with the schedule's expected
   trip count — ``framework.default_passes()``, so a newly registered
   pass joins automatically) plus :class:`PlannerContractPass`, which
   records prediction-vs-trace deltas in the same Finding schema
   ``graph_lint --json`` exports and FAILS the plan when the predicted
   HBM peak misses the traced estimate by more than the stated
   tolerance (default ±15%) or the predicted schedule tick count does
   not appear in the traced program.

Entry points: ``tools/auto_parallel.py`` (CLI, ``--smoke`` wired into
tier-1), ``plan_auto_parallel()`` (the JSON-shaped result), and
``graph_lint --planner`` (the CI section). PAPERS.md 2512.19250 is the
analyze->plan->verify shape; KForge (2606.02963) the
search-then-cache-the-winner discipline.
"""
from __future__ import annotations

import dataclasses
from dataclasses import field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .framework import (LintPass, Severity, aval_nbytes, default_passes,
                        register_pass, run_passes)

__all__ = ["PlanPoint", "PlanCost", "CostModel", "PlannerContractPass",
           "enumerate_plan_points", "price_plan_point",
           "plan_auto_parallel", "verify_plan", "point_config",
           "reference_step_costs", "PLAN_SCHEMA", "DEFAULT_TOLERANCE"]

PLAN_SCHEMA = "paddle_tpu.auto_parallel_plan/1"
DEFAULT_TOLERANCE = 0.15

#: PlanPoint.dtype values -> jnp dtypes (import-lazy)
PLAN_DTYPES = ("bfloat16", "float32")

#: the ONE statement of the CI smoke space: `tools/auto_parallel.py
#: --smoke` and `graph_lint --planner` both plan exactly this (tiny
#: config implied by the caller), so the two gates cannot drift onto
#: different spaces. ~20s on one CPU core.
SMOKE_KNOBS = dict(
    devices=4, batch_size=16, seq_len=8,
    hbm_budget_bytes=64 << 20, top=10,
    dtypes=("bfloat16",), zero_stages=(0, 1), vpp_choices=(1,))


@dataclasses.dataclass(frozen=True, order=True)
class PlanPoint:
    """One candidate configuration — the tuple a human used to pick."""
    dp: int
    tp: int
    pp: int
    vpp: int
    microbatches: int
    schedule: str       # "none" (pp=1) | a pp_schedule value
    zero_stage: int
    dtype: str          # "bfloat16" | "float32"

    def geometry(self) -> Dict[str, Any]:
        """The ``TRAIN_GEOMETRIES``-shaped dict ``build_train_target``
        consumes."""
        g = dict(dp=self.dp, tp=self.tp, pp=self.pp, vpp=self.vpp,
                 microbatches=self.microbatches,
                 zero_stage=self.zero_stage)
        if self.pp > 1:
            g["schedule"] = self.schedule
        return g

    def label(self) -> str:
        dt = {"bfloat16": "bf16", "float32": "f32"}.get(self.dtype,
                                                        self.dtype)
        core = (f"dp{self.dp}.tp{self.tp}.pp{self.pp}.V{self.vpp}"
                f".M{self.microbatches}")
        sched = self.schedule if self.pp > 1 else "-"
        return f"{core}.{sched}.z{self.zero_stage}.{dt}"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Rate constants for the step-time proxy. TPU-generation-shaped
    magnitudes used as RELATIVE ranking weights — the planner orders
    points, it does not promise wall-clock (the honest-costs discipline
    of docs/PERF.md: absolute numbers come from the bench harnesses on
    real chips)."""
    flops_per_sec: Dict[str, float] = field(
        default_factory=lambda: {"bfloat16": 2.0e14,
                                 "float32": 5.0e13})
    hbm_bytes_per_sec: float = 1.2e12
    ici_bytes_per_sec: float = 9.0e10

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class PlanCost:
    """One priced point: the per-device memory envelope and the
    step-time proxy decomposition (all seconds are proxy units)."""
    hbm_peak_bytes: int
    fits: bool
    step_time_proxy_s: float
    compute_s: float            # roofline max(flop, hbm) term
    bubble_s: float             # schedule inefficiency on top of compute
    comms_s: float              # explicit (traced) + analytic GSPMD terms
    efficiency: float           # schedule_efficiency (1.0 for pp=1)
    work_multiplier: float      # zb recompute etc. (already in compute_s)
    collective_bytes: int       # explicit traced collectives, scaled to B
    hbm_extrapolated: bool      # peak predicted through proxy batches
    ticks: Optional[int] = None  # schedule scan trips (pp>1)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# enumeration
# ---------------------------------------------------------------------------

def _factor_triples(n: int) -> List[Tuple[int, int, int]]:
    """All ordered (dp, tp, pp) with dp*tp*pp == n."""
    out = []
    for dp in range(1, n + 1):
        if n % dp:
            continue
        rest = n // dp
        for tp in range(1, rest + 1):
            if rest % tp:
                continue
            out.append((dp, tp, rest // tp))
    return out


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_plan_points(
        devices: int, cfg, batch_size: int, *,
        dtypes: Tuple[str, ...] = PLAN_DTYPES,
        zero_stages: Tuple[int, ...] = (0, 1, 3),
        schedules: Optional[Tuple[str, ...]] = None,
        vpp_choices: Tuple[int, ...] = (1, 2),
        microbatch_choices: Optional[Tuple[int, ...]] = None,
        max_microbatches: int = 32,
) -> Tuple[List[PlanPoint], Dict[str, int]]:
    """The legal configuration space for ``devices`` and this model,
    plus a per-reason count of pruned search BRANCHES (a mesh-level
    prune like tp-indivisible counts once for the whole subtree it
    kills, not once per leaf point — the reasons are the audit trail,
    the counts are branch counts). Microbatch counts above
    ``max_microbatches`` are a search-space bound, not a legality
    prune, and are not enumerated at all.

    ``schedules`` defaults to every entry of
    ``pipeline_async.SCHEDULE_INFO`` — a schedule added to the table is
    searched automatically. zero_stage=2 shares zero_stage=1's layout
    (``make_train_step``), so the default space skips it as a duplicate
    point, not as an illegal one.
    """
    from ..parallel.pipeline_async import (SCHEDULE_INFO,
                                           schedule_legality)
    if schedules is None:
        schedules = tuple(SCHEDULE_INFO)
    L = cfg.num_hidden_layers
    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    F, V_vocab = cfg.intermediate_size, cfg.vocab_size

    pruned: Dict[str, int] = {}

    def prune(reason: str):
        pruned[reason] = pruned.get(reason, 0) + 1

    points: List[PlanPoint] = []
    for dp, tp, pp in _factor_triples(int(devices)):
        if tp > 1 and (H % tp or Hkv % tp or F % tp or V_vocab % tp):
            prune("tp-indivisible (heads/ffn/vocab)")
            continue
        if pp == 1:
            # no pipeline: M=1, V=1, schedule not applicable
            if batch_size % dp:
                prune("batch-not-divisible-by-(M, dp)")
                continue
            for zero in zero_stages:
                if zero >= 1 and dp == 1:
                    prune("zero-needs-dp>1")
                    continue
                for dt in dtypes:
                    points.append(PlanPoint(dp, tp, pp, 1, 1, "none",
                                            zero, dt))
            continue
        m_choices = microbatch_choices or tuple(
            m for m in _divisors(batch_size) if m <= max_microbatches)
        for vpp in vpp_choices:
            if L % (pp * vpp):
                prune("layers-not-divisible-by-pp*vpp")
                continue
            for M in m_choices:
                if batch_size % M or (batch_size // M) % dp:
                    prune("batch-not-divisible-by-(M, dp)")
                    continue
                for sched in schedules:
                    reason = schedule_legality(
                        sched, num_stages=pp, num_microbatches=M,
                        virtual_chunks=vpp, dp=dp, tp=tp)
                    if reason is not None:
                        prune(f"schedule[{sched}]: "
                              f"{reason.splitlines()[0][:60]}")
                        continue
                    for zero in zero_stages:
                        if zero >= 1 and dp == 1:
                            prune("zero-needs-dp>1")
                            continue
                        for dt in dtypes:
                            points.append(PlanPoint(
                                dp, tp, pp, vpp, M, sched, zero, dt))
    return sorted(set(points)), pruned


# ---------------------------------------------------------------------------
# pricing
# ---------------------------------------------------------------------------

def point_config(base_cfg, point: PlanPoint):
    """The model config a point's train step runs with (flash/fused
    kernels off: the planner traces on the host, and the passes are
    structural — kernel choice changes nothing they price)."""
    import jax.numpy as jnp
    dt = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[point.dtype]
    return dataclasses.replace(
        base_cfg, dtype=dt, pp_stages=point.pp, vpp_chunks=point.vpp,
        num_microbatches=point.microbatches,
        pp_schedule=(point.schedule if point.pp > 1 else "gpipe"),
        use_flash_attention=False, use_fused_norm_rope=False,
        remat=False)


def _model_bytes(cfg) -> int:
    """Total parameter bytes at cfg.dtype (abstract, nothing inits)."""
    import jax
    from ..models.llama import abstract_params
    leaves = jax.tree_util.tree_leaves(abstract_params(cfg))
    return sum(aval_nbytes(x) for x in leaves)


def reference_step_costs(base_cfg, dtype: str, seq_len: int,
                         batch_rows: int = 4) -> Dict[str, Any]:
    """Per-batch-row flops/bytes of the single-device train step — ONE
    real compile per dtype feeds every point's step-time proxy.

    Uses ``hbm.xla_cost_analysis`` (the shared normalizer); when the
    backend omits the counters the proxy degrades to a closed-form
    transformer estimate (6*N flops/token forward+backward, parameter
    + activation traffic) rather than crashing — ``source`` records
    which model priced the run.
    """
    import jax
    import jax.numpy as jnp
    from ..models import llama as L
    from ..parallel.mesh import init_hybrid_mesh
    from .hbm import xla_cost_analysis

    cfg1 = point_config(base_cfg, PlanPoint(1, 1, 1, 1, 1, "none", 0,
                                            dtype))
    pbytes = _model_bytes(cfg1)
    n_params = pbytes // jnp.dtype(cfg1.dtype).itemsize
    flops = bytes_ = None
    try:
        hm = init_hybrid_mesh(dp=1, pp=1, tp=1, set_global=False)
        step_fn, init_fn = L.make_train_step(cfg1, hm.mesh)
        state = jax.eval_shape(
            lambda: init_fn(jax.random.PRNGKey(0)))
        sds = jax.ShapeDtypeStruct
        batch = {"tokens": sds((batch_rows, seq_len), jnp.int32),
                 "labels": sds((batch_rows, seq_len), jnp.int32)}
        compiled = step_fn.lower(state, batch).compile()
        ca = xla_cost_analysis(compiled)
        flops = ca.get("flops")
        bytes_ = ca.get("bytes accessed")
    except Exception:
        pass  # backend without compile support: analytic fallback below
    if flops and flops > 0 and bytes_ and bytes_ > 0:
        return {"flops_per_row": float(flops) / batch_rows,
                "bytes_per_row": float(bytes_) / batch_rows,
                "param_bytes": pbytes,
                "source": "xla_cost_analysis"}
    # closed-form fallback: 6*N flops per token (fwd 2N + bwd 4N),
    # traffic = 3x params (read + grad write + update) amortized per
    # row at the reference batch, plus per-token activation traffic
    act_row = (12 * cfg1.num_hidden_layers * seq_len
               * cfg1.hidden_size * jnp.dtype(cfg1.dtype).itemsize)
    return {"flops_per_row": 6.0 * float(n_params) * seq_len,
            "bytes_per_row": 3.0 * pbytes / batch_rows + act_row,
            "param_bytes": pbytes,
            "source": "analytic-fallback"}


def _min_proxy_batch(point: PlanPoint) -> int:
    """Smallest batch the point's step traces with: M microbatches of
    dp rows each."""
    return point.microbatches * point.dp


def _trace_point(point: PlanPoint, base_cfg, batch_size: int,
                 seq_len: int, cache: Dict):
    """Abstract-trace the point's train step at ``batch_size`` —
    cached, zero compiles. Returns the GraphTarget."""
    key = (point, batch_size, seq_len)
    tgt = cache.get(key)
    if tgt is None:
        from .training_graphs import build_train_target
        tgt = build_train_target(
            point.geometry(), f"planner[{point.label()}]",
            batch_size=batch_size, seq_len=seq_len,
            cfg=point_config(base_cfg, point))
        cache[key] = tgt
    return tgt


def price_plan_point(point: PlanPoint, base_cfg, *, batch_size: int,
                     seq_len: int, hbm_budget_bytes: Optional[int],
                     ref_costs: Dict[str, Dict],
                     cost_model: Optional[CostModel] = None,
                     trace_cache: Optional[Dict] = None) -> PlanCost:
    """Price one legal point. ``ref_costs[dtype]`` comes from
    :func:`reference_step_costs`; ``trace_cache`` is shared across
    points (and with verification) so nothing traces twice."""
    from ..parallel.pipeline_1f1b import (schedule_efficiency,
                                          schedule_ticks)
    from ..parallel.pipeline_async import PP_SCHEDULES, SCHEDULE_INFO
    from .collectives import collective_cost_bytes
    from .hbm import estimate_hbm_peak

    model = cost_model or CostModel()
    cache = trace_cache if trace_cache is not None else {}
    B = int(batch_size)

    # ---- HBM peak: trace at proxy batches, extrapolate to B ---------
    b1 = _min_proxy_batch(point)
    b2 = 2 * b1
    extrapolated = B > b2
    if not extrapolated:
        tgt = _trace_point(point, base_cfg, B, seq_len, cache)
        peak = estimate_hbm_peak(tgt).peak_bytes
        coll_b = collective_cost_bytes(tgt.jaxpr)
    else:
        t1 = _trace_point(point, base_cfg, b1, seq_len, cache)
        t2 = _trace_point(point, base_cfg, b2, seq_len, cache)
        p1 = estimate_hbm_peak(t1).peak_bytes
        p2 = estimate_hbm_peak(t2).peak_bytes
        slope = max(0, p2 - p1) / (b2 - b1)
        peak = int(p1 + slope * (B - b1))
        # explicit collective payloads split into batch-scaling
        # microbatch activations (the ppermute pairs, in-body tp
        # all-reduces) and batch-INDEPENDENT terms (the composed
        # schedules' folded dp gradient psum is param-shaped) — the
        # same two-proxy-point affine extrapolation as the HBM peak
        # separates slope from intercept instead of scaling both
        c1 = collective_cost_bytes(t1.jaxpr)
        c2 = collective_cost_bytes(t2.jaxpr)
        c_slope = max(0, c2 - c1) / (b2 - b1)
        coll_b = int(c1 + c_slope * (B - b1))
    fits = (hbm_budget_bytes is None
            or peak <= int(hbm_budget_bytes))

    # ---- step-time proxy --------------------------------------------
    ref = ref_costs[point.dtype]
    shard = point.dp * point.tp * point.pp
    if point.pp > 1:
        info = SCHEDULE_INFO[point.schedule]
        work_mult = info.work_units_per_mb_stage / 4.0
        eff = schedule_efficiency(
            point.pp, point.microbatches, point.vpp,
            schedule=PP_SCHEDULES[point.schedule][0])
        ticks = schedule_ticks(
            point.pp, point.microbatches, point.vpp,
            schedule=PP_SCHEDULES[point.schedule][0])
    else:
        work_mult, eff, ticks = 1.0, 1.0, None
    flops_dev = ref["flops_per_row"] * B / shard * work_mult
    bytes_dev = ref["bytes_per_row"] * B / shard
    compute_s = max(flops_dev / model.flops_per_sec[point.dtype],
                    bytes_dev / model.hbm_bytes_per_sec)
    bubble_s = compute_s * (1.0 / eff - 1.0)

    # ---- comms: traced explicit + analytic GSPMD terms --------------
    comms_bytes = float(coll_b)
    # param bytes depend only on dtype — reference_step_costs already
    # computed them once per dtype
    pbytes_dev = ref["param_bytes"] / (point.tp * point.pp)
    # composed async points (r19) carry their dp gradient psum and tp
    # activation all-reduces EXPLICITLY in the traced program (the
    # shard_map body's manual collectives, already in coll_b above) —
    # the analytic terms below model only what GSPMD still inserts at
    # compile time, so adding them for those points would double-count
    async_exec = (point.pp > 1
                  and SCHEDULE_INFO[point.schedule].executor is not None)
    if point.dp > 1 and not async_exec:
        # gradient all-reduce (ZeRO>=1: reduce-scatter + gather moves
        # the same total wire bytes)
        comms_bytes += 2.0 * (point.dp - 1) / point.dp * pbytes_dev
    if point.dp > 1 and point.zero_stage >= 3:
        # parameter regather at use (fwd) + re-scatter of updates
        # (outside the shard_map even for composed async points)
        comms_bytes += 2.0 * (point.dp - 1) / point.dp * pbytes_dev
    if point.tp > 1 and not async_exec:
        import jax.numpy as jnp
        act = (B / point.dp) * seq_len * base_cfg.hidden_size \
            * jnp.dtype(point.dtype).itemsize
        # 2 all-reduces (attn-out + mlp-down) fwd and bwd per layer
        layers_dev = base_cfg.num_hidden_layers / point.pp
        comms_bytes += (4.0 * layers_dev * act
                        * 2.0 * (point.tp - 1) / point.tp)
    comms_s = comms_bytes / model.ici_bytes_per_sec

    return PlanCost(
        hbm_peak_bytes=int(peak), fits=fits,
        step_time_proxy_s=compute_s + bubble_s + comms_s,
        compute_s=compute_s, bubble_s=bubble_s, comms_s=comms_s,
        efficiency=round(float(eff), 6), work_multiplier=work_mult,
        collective_bytes=int(coll_b), hbm_extrapolated=extrapolated,
        ticks=ticks)


# ---------------------------------------------------------------------------
# verification: the winner is checked, not trusted
# ---------------------------------------------------------------------------

@register_pass
class PlannerContractPass(LintPass):
    """Prediction-vs-trace contract for a planned configuration.

    Runs on targets carrying ``meta['planner_plan']`` (the planner's
    priced prediction for exactly this geometry) and no-ops everywhere
    else, so registering it globally costs the lint suites nothing.
    Checks, each exported in the shared Finding schema:

    * predicted HBM peak within ``tolerance`` of the traced
      ``estimate_hbm_peak`` (ERROR beyond — the plan's memory model is
      wrong and its fits/doesn't-fit answer cannot be trusted);
    * the predicted schedule tick count appears among the traced scan
      trip counts (ERROR otherwise — the priced schedule is not the
      schedule that would run);
    * an INFO record of every delta (peak, ticks, traced explicit
      collective bytes vs the scaled prediction) — the CLI and
      ``graph_lint --json`` surface these as machine-readable
      prediction-quality telemetry (``self.deltas`` keeps the numbers
      per target for the JSON report).
    """

    name = "planner-contract"

    def __init__(self, tolerance: float = DEFAULT_TOLERANCE):
        self.tolerance = float(tolerance)
        self.deltas: Dict[str, Dict[str, Any]] = {}

    def run(self, target):
        plan = target.meta.get("planner_plan")
        if plan is None:
            return []
        from .collectives import collective_cost_bytes, scan_trip_counts
        from .hbm import estimate_hbm_peak
        findings = []
        est = estimate_hbm_peak(target)
        pred = int(plan["hbm_peak_bytes"])
        rel = ((pred - est.peak_bytes) / est.peak_bytes
               if est.peak_bytes else 0.0)
        rec: Dict[str, Any] = {
            "predicted_hbm_peak_bytes": pred,
            "traced_hbm_peak_bytes": est.peak_bytes,
            "hbm_rel_delta": round(rel, 6),
            "tolerance": self.tolerance,
        }
        findings.append(self.finding(
            target,
            f"predicted HBM peak {pred / 2**20:.2f} MiB vs traced "
            f"{est.peak_bytes / 2**20:.2f} MiB "
            f"(delta {rel:+.1%}, tolerance ±{self.tolerance:.0%})",
            severity=Severity.INFO))
        if abs(rel) > self.tolerance:
            findings.append(self.finding(
                target,
                f"planner HBM prediction off by {rel:+.1%} "
                f"(> ±{self.tolerance:.0%}): predicted "
                f"{pred / 2**20:.2f} MiB, traced estimate "
                f"{est.peak_bytes / 2**20:.2f} MiB — the plan's "
                f"fits-in-budget answer is untrustworthy"))
        ticks = plan.get("ticks")
        if ticks is not None:
            trips = scan_trip_counts(target.jaxpr)
            rec["predicted_ticks"] = int(ticks)
            rec["traced_scan_trips"] = sorted(set(trips))
            if int(ticks) not in trips:
                findings.append(self.finding(
                    target,
                    f"planned schedule prices {ticks} ticks but the "
                    f"traced program scans {sorted(set(trips))} — the "
                    f"priced schedule is not the schedule that runs"))
            else:
                findings.append(self.finding(
                    target, f"schedule tick count {ticks} confirmed "
                            f"in the traced program",
                    severity=Severity.INFO))
        pred_coll = plan.get("collective_bytes")
        if pred_coll is not None:
            traced_coll = collective_cost_bytes(target.jaxpr)
            rec["predicted_collective_bytes"] = int(pred_coll)
            rec["traced_collective_bytes"] = int(traced_coll)
            findings.append(self.finding(
                target,
                f"explicit collective bytes: predicted {pred_coll} "
                f"vs traced {traced_coll} (informational — GSPMD "
                f"collectives are not in either)",
                severity=Severity.INFO))
        self.deltas[target.name] = rec
        return findings


def verify_plan(point: PlanPoint, base_cfg, *, batch_size: int,
                seq_len: int, hbm_budget_bytes: Optional[int],
                prediction: Dict[str, Any],
                tolerance: float = DEFAULT_TOLERANCE,
                trace_cache: Optional[Dict] = None) -> Dict[str, Any]:
    """Trace ``point`` at the FULL requested batch and run the complete
    registered pass stack plus the planner contract over it. Returns
    the verification report: ``ok`` (no ERROR from any pass), the
    findings in the shared JSON schema, and the contract deltas."""
    from .training_graphs import build_train_target
    cache = trace_cache if trace_cache is not None else {}
    key = (point, int(batch_size), int(seq_len))
    target = cache.get(key)
    if target is None:
        target = build_train_target(
            point.geometry(), f"planner.winner[{point.label()}]",
            batch_size=batch_size, seq_len=seq_len,
            cfg=point_config(base_cfg, point),
            hbm_budget_bytes=hbm_budget_bytes)
    elif hbm_budget_bytes is not None:
        target.meta["hbm_budget_bytes"] = int(hbm_budget_bytes)
    target.meta["planner_plan"] = dict(prediction)
    contract = PlannerContractPass(tolerance=tolerance)
    passes = [p for p in default_passes()
              if p.name != contract.name] + [contract]
    report = run_passes(passes, [target])
    return {
        "point": point.to_dict(),
        "graph": target.name,
        "ok": report.ok,
        "tolerance": tolerance,
        "deltas": contract.deltas.get(target.name, {}),
        "report": report.to_dict(),
    }


# ---------------------------------------------------------------------------
# the decision procedure
# ---------------------------------------------------------------------------

def plan_auto_parallel(
        base_cfg, devices: int, *, batch_size: int, seq_len: int = 128,
        hbm_budget_bytes: Optional[int] = None, top: int = 20,
        verify: bool = True, tolerance: float = DEFAULT_TOLERANCE,
        cost_model: Optional[CostModel] = None,
        progress: Optional[Callable[[str], None]] = None,
        **enumerate_kw) -> Dict[str, Any]:
    """Enumerate -> price -> rank -> verify; returns the plan JSON
    (schema ``paddle_tpu.auto_parallel_plan/1``).

    ``enumerate_kw`` forwards to :func:`enumerate_plan_points`
    (dtypes, zero_stages, schedules, vpp/microbatch choices) — the
    smoke mode narrows the space through these."""
    say = progress or (lambda *_: None)
    model = cost_model or CostModel()
    points, pruned = enumerate_plan_points(
        devices, base_cfg, batch_size, **enumerate_kw)
    say(f"search space: {len(points)} legal points "
        f"({sum(pruned.values())} pruned)")

    dtypes_used = sorted({p.dtype for p in points})
    ref_costs = {}
    for dt in dtypes_used:
        ref_costs[dt] = reference_step_costs(base_cfg, dt, seq_len)
        say(f"reference step [{dt}]: "
            f"{ref_costs[dt]['flops_per_row'] / 1e6:.1f} MFLOP/row "
            f"({ref_costs[dt]['source']})")

    trace_cache: Dict = {}
    priced: List[Tuple[PlanPoint, PlanCost]] = []
    trace_failed: Dict[str, int] = {}
    for i, pt in enumerate(points):
        try:
            cost = price_plan_point(
                pt, base_cfg, batch_size=batch_size, seq_len=seq_len,
                hbm_budget_bytes=hbm_budget_bytes, ref_costs=ref_costs,
                cost_model=model, trace_cache=trace_cache)
        except Exception as e:  # a point the executors reject late
            reason = f"trace-failed: {type(e).__name__}"
            trace_failed[reason] = trace_failed.get(reason, 0) + 1
            continue
        priced.append((pt, cost))
        if progress and (i + 1) % 10 == 0:
            say(f"priced {i + 1}/{len(points)}")

    fitting = [(p, c) for p, c in priced if c.fits]
    fitting.sort(key=lambda pc: (pc[1].step_time_proxy_s,
                                 pc[1].hbm_peak_bytes))
    over = len(priced) - len(fitting)
    say(f"{len(fitting)} plans fit the budget ({over} over)")

    plans = [{"rank": i + 1, "point": p.to_dict(),
              "label": p.label(), "cost": c.to_dict()}
             for i, (p, c) in enumerate(fitting[:max(int(top), 1)])]
    out: Dict[str, Any] = {
        "schema": PLAN_SCHEMA,
        "model": {
            "hidden_size": base_cfg.hidden_size,
            "layers": base_cfg.num_hidden_layers,
            "heads": base_cfg.num_attention_heads,
            "kv_heads": base_cfg.num_key_value_heads,
            "vocab": base_cfg.vocab_size,
            "param_bytes_bf16": _model_bytes(point_config(
                base_cfg, PlanPoint(1, 1, 1, 1, 1, "none", 0,
                                    "bfloat16"))),
        },
        "devices": int(devices), "batch_size": int(batch_size),
        "seq_len": int(seq_len),
        "hbm_budget_bytes": (int(hbm_budget_bytes)
                             if hbm_budget_bytes is not None else None),
        "cost_model": model.to_dict(),
        "reference_costs": ref_costs,
        # invariant a JSON consumer can audit: enumerated == legal +
        # sum(pruned branches); trace-failed points stay in `legal`
        # (they passed enumeration) and are reported separately
        "enumerated": len(points) + sum(pruned.values()),
        "legal": len(points), "priced": len(priced),
        "pruned": dict(sorted(pruned.items())),
        "trace_failed": dict(sorted(trace_failed.items())),
        "over_budget": over,
        "plans": plans,
        "winner": plans[0] if plans else None,
    }
    if not fitting:
        out["verification"] = {
            "ok": False,
            "reason": "no legal configuration fits the budget"}
        return out
    if verify:
        win_pt, win_cost = fitting[0]
        say(f"verifying winner {win_pt.label()} at full batch "
            f"{batch_size}")
        prediction = dict(win_cost.to_dict(), point=win_pt.to_dict())
        out["verification"] = verify_plan(
            win_pt, base_cfg, batch_size=batch_size, seq_len=seq_len,
            hbm_budget_bytes=hbm_budget_bytes, prediction=prediction,
            tolerance=tolerance, trace_cache=trace_cache)
    return out
